"""Pallas kernel numerics vs the jnp reference path (interpret mode on CPU) —
the per-op equivalence discipline of the MKLDNN tester (SURVEY.md §8.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas_kernels import flash_attention


def _full_attention(q, k, v, causal=False):
    B, T, H, D = q.shape
    s = jnp.einsum("bthd,bshd->bhts", q, k) * (D ** -0.5)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("T", [32, 48])   # 48 exercises the padded-tail path
def test_flash_attention_matches_reference(causal, T):
    rng = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(rng, 3)
    B, H, D = 2, 2, 16
    q = jax.random.normal(kq, (B, T, H, D))
    k = jax.random.normal(kk, (B, T, H, D))
    v = jax.random.normal(kv, (B, T, H, D))
    ref = _full_attention(q, k, v, causal)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_attention_jits_and_grads():
    rng = jax.random.PRNGKey(1)
    q = jax.random.normal(rng, (1, 32, 2, 16))

    def loss(q):
        return jnp.sum(flash_attention(q, q, q, causal=True, block_q=16,
                                       block_k=16, interpret=True))

    g = jax.jit(jax.grad(loss))(q)
    assert np.isfinite(np.asarray(g)).all()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("T", [32, 48])   # 48 exercises the padded-tail path
def test_flash_backward_kernels_match_reference(causal, T):
    """The Pallas dq / dkv kernels vs autodiff through dense attention —
    the grad-side analog of the MKLDNN equivalence discipline."""
    rng = jax.random.PRNGKey(7)
    kq, kk, kv, kg = jax.random.split(rng, 4)
    B, H, D = 2, 2, 16
    q = jax.random.normal(kq, (B, T, H, D))
    k = jax.random.normal(kk, (B, T, H, D))
    v = jax.random.normal(kv, (B, T, H, D))
    g = jax.random.normal(kg, (B, T, H, D))

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=16,
                                       block_k=16, interpret=True) * g)

    def f_ref(q, k, v):
        return jnp.sum(_full_attention(q, k, v, causal) * g)

    got = jax.grad(f, (0, 1, 2))(q, k, v)
    want = jax.grad(f_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_backward_no_dense_scores_in_jaxpr():
    """The [T, T] score matrix must not materialise in HBM in the backward
    jaxpr (the round-1 fallback recomputed dense attention)."""
    T = 64
    q = jnp.zeros((1, T, 1, 16))

    def loss(q):
        return jnp.sum(flash_attention(q, q, q, block_q=16, block_k=16,
                                       interpret=True))

    jaxpr = jax.make_jaxpr(jax.grad(loss))(q)
    for eqn in jaxpr.jaxpr.eqns:
        for var in eqn.outvars:
            shape = getattr(var.aval, "shape", ())
            assert not (len(shape) >= 2 and shape[-1] == T and
                        shape[-2] == T), f"dense [T,T] tensor in bwd: {eqn}"
