"""Pallas kernel numerics vs the jnp reference path (interpret mode on CPU) —
the per-op equivalence discipline of the MKLDNN tester (SURVEY.md §8.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas_kernels import flash_attention


def _full_attention(q, k, v, causal=False):
    B, T, H, D = q.shape
    s = jnp.einsum("bthd,bshd->bhts", q, k) * (D ** -0.5)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("T", [32, 48])   # 48 exercises the padded-tail path
def test_flash_attention_matches_reference(causal, T):
    rng = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(rng, 3)
    B, H, D = 2, 2, 16
    q = jax.random.normal(kq, (B, T, H, D))
    k = jax.random.normal(kk, (B, T, H, D))
    v = jax.random.normal(kv, (B, T, H, D))
    ref = _full_attention(q, k, v, causal)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_attention_jits_and_grads():
    rng = jax.random.PRNGKey(1)
    q = jax.random.normal(rng, (1, 32, 2, 16))

    def loss(q):
        return jnp.sum(flash_attention(q, q, q, causal=True, block_q=16,
                                       block_k=16, interpret=True))

    g = jax.jit(jax.grad(loss))(q)
    assert np.isfinite(np.asarray(g)).all()
