"""Distributed-logic tests on the 8-device virtual CPU mesh.

Mirrors the reference's in-process multi-node testing strategy (SURVEY.md §4.3:
pservers on localhost ports, MultiGradientMachine with threads): every sharding
and collective path runs here without hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddle_tpu import parallel as pp
from paddle_tpu.nn import Linear, Module, Sequential
from paddle_tpu.optimizer import SGD, Adam


def test_make_mesh_axes_and_wildcard():
    mesh = pp.make_mesh(data=-1)
    assert mesh.shape == {"data": 8}
    mesh = pp.make_mesh(data=4, model=2)
    assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2
    # model axis must be innermost (nearest-neighbour ICI)
    assert mesh.axis_names[-1] == "model"
    with pytest.raises(ValueError):
        pp.make_mesh(data=3, model=3)


def test_collectives_roundtrip():
    mesh = pp.make_mesh(data=8)

    def f(x):
        s = pp.all_reduce(x, "data")
        g = pp.all_gather(x, "data")
        rs = pp.reduce_scatter(g, "data")
        idx = pp.axis_index("data")
        nxt = pp.permute_ring(idx.astype(jnp.float32).reshape(1), "data")
        return s, g, rs, nxt

    x = jnp.arange(8.0)
    fn = pp.shard_map(f, mesh=mesh, in_specs=P("data"),
                       out_specs=(P("data"), P("data"), P("data"), P("data")))
    s, g, rs, nxt = fn(x)
    np.testing.assert_allclose(s, np.full(8, 28.0))          # sum 0..7 bcast
    np.testing.assert_allclose(np.asarray(g)[:8], np.arange(8.0))  # gathered copy
    # each device held a full arange(8) after gather; scatter-sum gives 8*i
    np.testing.assert_allclose(rs, 8.0 * np.arange(8.0))
    # ring: device i receives index of device i-1
    np.testing.assert_allclose(np.sort(np.asarray(nxt)), np.arange(8.0))


def _toy_data(n=64, din=12, classes=3, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, din).astype(np.float32)
    w = rs.randn(din, classes).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


class _Net(Module):
    def __init__(self):
        super().__init__()
        self.l1 = Linear(12, 32, act=jax.nn.relu)
        self.l2 = Linear(32, 3)

    def __call__(self, params, x, **kw):
        return self.l2(params["l2"], self.l1(params["l1"], x))


def _loss_fn(model):
    def loss(params, x, y):
        logits = model(params, x)
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
    return loss


def test_data_parallel_matches_single_device():
    """Equivalence test in the spirit of test_CompareSparse.cpp (SURVEY §4.2):
    8-way DP over the mesh must reproduce single-device full-batch training."""
    x, y = _toy_data()
    model = _Net()
    params0 = model.init(jax.random.PRNGKey(1))
    loss = _loss_fn(model)

    # single-device run
    opt = SGD(0.1)
    state = opt.init(params0)
    p_ref = params0
    for _ in range(5):
        _, grads = jax.value_and_grad(loss)(p_ref, x, y)
        p_ref, state = opt.update(grads, state, p_ref)

    # 8-way data parallel
    dp = pp.DataParallel(loss, SGD(0.1), mesh=pp.make_mesh(data=8))
    p, s = dp.init(model.init(jax.random.PRNGKey(1)))
    bx, by = dp.shard_batch((x, y))
    for _ in range(5):
        p, s, l = dp.step(p, s, bx, by)

    for (k1, a), (k2, b) in zip(Module.named_parameters(p_ref),
                                Module.named_parameters(jax.device_get(p))):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-5), k1


def test_zero1_matches_plain_dp():
    """TRUE ZeRO-1 (reduce-scatter grads, shard-local optimizer, all-gather
    params) must train identically to plain replicated-optimizer DP."""
    x, y = _toy_data()
    model = _Net()
    loss = _loss_fn(model)
    dp0 = pp.DataParallel(loss, Adam(1e-2), mesh=pp.make_mesh(data=8))
    z = pp.Zero1DataParallel(loss, Adam(1e-2), mesh=pp.make_mesh(data=8))
    pa, sa = dp0.init(model.init(jax.random.PRNGKey(2)))
    zs = z.init(model.init(jax.random.PRNGKey(2)))
    ba = dp0.shard_batch((x, y))
    for _ in range(3):
        pa, sa, _ = dp0.step(pa, sa, *ba)
        zs, _ = z.step(zs, *ba)
    pb = z.params(zs)
    for (_, a), (_, b) in zip(Module.named_parameters(jax.device_get(pa)),
                              Module.named_parameters(pb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


def test_zero1_optimizer_state_is_partitioned():
    """The point of ZeRO-1: every optimizer slot buffer (and the master flat
    param vector) is sharded over the data axis — each device holds 1/n."""
    from jax.sharding import PartitionSpec as P
    x, y = _toy_data()
    model = _Net()
    z = pp.Zero1DataParallel(_loss_fn(model), Adam(1e-2),
                             mesh=pp.make_mesh(data=8))
    zs = z.init(model.init(jax.random.PRNGKey(0)))
    zs, _ = z.step(zs, *z.shard_batch((x, y)))

    def assert_sharded(arr):
        assert arr.sharding.spec == P("data"), arr.sharding
        local = arr.addressable_shards[0].data
        assert local.shape[0] * 8 == arr.shape[0]

    assert_sharded(zs.flat)
    for leaf in jax.tree_util.tree_leaves(zs.opt_state["slots"]):
        assert_sharded(leaf)


def test_tensor_parallel_linear_matches_dense():
    mesh = pp.make_mesh(data=2, model=4)

    class TPNet(Module):
        def __init__(self):
            super().__init__()
            self.up = pp.ColumnParallelLinear(16, 64, act=jax.nn.relu)
            self.down = pp.RowParallelLinear(64, 8)

        def __call__(self, params, x, **kw):
            return self.down(params["down"], self.up(params["up"], x))

    net = TPNet()
    params = net.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 16))
    ref = net(params, x)  # no mesh: plain dense math

    rules = pp.ShardingRules([(pat, spec) for pat, spec in
                              pp.tensor_parallel.collect_tp_rules(net)] +
                             [(r".*", P())])
    sp = rules.apply(mesh, params)
    xs = pp.shard_batch(x, mesh, "data")
    with mesh:
        out = jax.jit(net)(sp, xs)
    np.testing.assert_allclose(jax.device_get(out), jax.device_get(ref),
                               rtol=1e-5, atol=1e-5)


def test_sharded_embedding_lookup():
    mesh = pp.make_mesh(model=8)
    emb = pp.ShardedEmbedding(64, 16)
    params = emb.init(jax.random.PRNGKey(0))
    ids = jnp.array([0, 5, 63, 17])
    ref = jnp.take(params["table"], ids, axis=0)
    sp = pp.ShardingRules(pp.tensor_parallel.collect_tp_rules(emb)).apply(mesh, params)
    with mesh:
        out = jax.jit(emb)(sp, ids)
    np.testing.assert_allclose(jax.device_get(out), jax.device_get(ref), rtol=1e-6)


def _full_attention(q, k, v, causal=False):
    B, T, H, D = q.shape
    s = jnp.einsum("bthd,bshd->bhts", q, k) * (D ** -0.5)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_exact(causal):
    mesh = pp.make_mesh(seq=8)
    rng = jax.random.PRNGKey(3)
    kq, kk, kv = jax.random.split(rng, 3)
    B, T, H, D = 2, 64, 4, 8
    q = jax.random.normal(kq, (B, T, H, D))
    k = jax.random.normal(kk, (B, T, H, D))
    v = jax.random.normal(kv, (B, T, H, D))
    ref = _full_attention(q, k, v, causal)
    out = pp.ring_self_attention(mesh, q, k, v, causal=causal)
    np.testing.assert_allclose(jax.device_get(out), jax.device_get(ref),
                               rtol=2e-4, atol=2e-4)


def test_zigzag_fallback_warns_and_stays_exact():
    """T not divisible by 2*n forces the contiguous causal layout; the
    fallback must be loud (it wastes ~half the FLOPs) and still correct."""
    mesh = pp.make_mesh(seq=8)
    rng = jax.random.PRNGKey(9)
    kq, kk, kv = jax.random.split(rng, 3)
    B, T, H, D = 1, 40, 2, 8          # 40 % 16 != 0 but 40 % 8 == 0
    q = jax.random.normal(kq, (B, T, H, D))
    k = jax.random.normal(kk, (B, T, H, D))
    v = jax.random.normal(kv, (B, T, H, D))
    with pytest.warns(UserWarning, match="CONTIGUOUS causal layout"):
        out = pp.ring_self_attention(mesh, q, k, v, causal=True)
    ref = _full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(jax.device_get(out), jax.device_get(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_attention_matches_full(causal):
    rng = jax.random.PRNGKey(4)
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (2, 48, 2, 8))
    k = jax.random.normal(kk, (2, 48, 2, 8))
    v = jax.random.normal(kv, (2, 48, 2, 8))
    ref = _full_attention(q, k, v, causal)
    out = pp.blockwise_attention(q, k, v, block_size=16, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_ulysses_attention_matches_full():
    mesh = pp.make_mesh(seq=8)
    rng = jax.random.PRNGKey(5)
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (2, 64, 8, 4))
    k = jax.random.normal(kk, (2, 64, 8, 4))
    v = jax.random.normal(kv, (2, 64, 8, 4))
    ref = _full_attention(q, k, v)
    out = pp.ulysses_attention(mesh, q, k, v)
    np.testing.assert_allclose(jax.device_get(out), jax.device_get(ref),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_matches_sequential():
    mesh = pp.make_mesh(pipe=4)
    stage = pp.PipelineStage(lambda: Linear(16, 16, act=jnp.tanh), n_stages=4)
    params = stage.init(jax.random.PRNGKey(6))
    x = jax.random.normal(jax.random.PRNGKey(7), (8, 16))
    ref = stage(params, x)  # sequential scan over stages

    def stage_fn(p, mb):
        return jnp.tanh(mb @ p["w"] + p["b"])

    run = pp.pipeline_spmd(stage_fn, mesh, n_microbatches=4)
    with mesh:
        out = run(params, x)
    np.testing.assert_allclose(jax.device_get(out), jax.device_get(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_trains():
    """Autodiff flows through the ppermute pipeline."""
    mesh = pp.make_mesh(pipe=4)
    stage = pp.PipelineStage(lambda: Linear(8, 8, act=jnp.tanh), n_stages=4)
    params = stage.init(jax.random.PRNGKey(8))
    x = jax.random.normal(jax.random.PRNGKey(9), (8, 8))
    y = jax.random.normal(jax.random.PRNGKey(10), (8, 8))

    def stage_fn(p, mb):
        return jnp.tanh(mb @ p["w"] + p["b"])

    run = pp.pipeline_spmd(stage_fn, mesh, n_microbatches=2)

    def loss(params):
        return jnp.mean((run(params, x) - y) ** 2)

    with mesh:
        l0 = loss(params)
        g = jax.grad(loss)(params)
        params2 = jax.tree_util.tree_map(lambda p, gg: p - 0.5 * gg, params, g)
        l1 = loss(params2)
    assert float(l1) < float(l0)


@pytest.mark.parametrize("n_micro", [4, 6])
def test_pipeline_1f1b_matches_sequential_grads(n_micro):
    """1F1B loss and per-stage grads equal the unpipelined computation.

    Also the schedule-accounting claim: the timetable interleaves so at most
    n_stages microbatch inputs are ever stashed (the buffer IS n_stages
    slots), vs GPipe's all-M stash."""
    S = 4
    mesh = pp.make_mesh(pipe=S)
    stage = pp.PipelineStage(lambda: Linear(8, 8, act=jnp.tanh), n_stages=S)
    params = stage.init(jax.random.PRNGKey(11))
    B = 8 * n_micro // 4
    x = jax.random.normal(jax.random.PRNGKey(12), (B, 8))
    y = jax.random.normal(jax.random.PRNGKey(13), (B, 8))

    def stage_fn(p, mb):
        return jnp.tanh(mb @ p["w"] + p["b"])

    def loss_fn(out, y_mb):
        return jnp.mean((out - y_mb) ** 2)

    def seq_loss(params):
        # mean of per-microbatch losses == 1F1B's accumulation
        mbx = x.reshape(n_micro, B // n_micro, 8)
        mby = y.reshape(n_micro, B // n_micro, 8)
        total = 0.0
        for m in range(n_micro):
            h = mbx[m]
            for si in range(S):
                h = stage_fn(jax.tree_util.tree_map(lambda p: p[si], params),
                             h)
            total = total + loss_fn(h, mby[m])
        return total / n_micro

    ref_loss = seq_loss(params)
    ref_grads = jax.grad(seq_loss)(params)

    step = pp.pipeline_1f1b(stage_fn, loss_fn, mesh, n_microbatches=n_micro)
    with mesh:
        loss, grads = step(params, x, y)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-5, atol=1e-6)
    jax.tree_util.tree_map(
        lambda g, r: np.testing.assert_allclose(
            jax.device_get(g), jax.device_get(r), rtol=1e-4, atol=1e-5),
        grads, ref_grads)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_grads_match_full(causal):
    """Ring attention's hand-written backward (Pallas block-gradient kernels
    with dk/dv accumulators riding the ppermute ring) vs autodiff through
    dense attention."""
    mesh = pp.make_mesh(seq=4)
    rng = jax.random.PRNGKey(11)
    kq, kk, kv, kg = jax.random.split(rng, 4)
    B, T, H, D = 2, 32, 2, 8
    q = jax.random.normal(kq, (B, T, H, D))
    k = jax.random.normal(kk, (B, T, H, D))
    v = jax.random.normal(kv, (B, T, H, D))
    g = jax.random.normal(kg, (B, T, H, D))

    def f(q, k, v):
        return jnp.sum(pp.ring_self_attention(mesh, q, k, v, causal=causal) * g)

    def f_ref(q, k, v):
        return jnp.sum(_full_attention(q, k, v, causal) * g)

    got = jax.grad(f, (0, 1, 2))(q, k, v)
    want = jax.grad(f_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        np.testing.assert_allclose(jax.device_get(a), jax.device_get(b),
                                   rtol=2e-4, atol=2e-4)


def test_ring_attention_contiguous_layout_still_exact():
    """layout="contiguous" keeps the original (discard-future-blocks)
    behavior as an explicit opt-out from zigzag."""
    mesh = pp.make_mesh(seq=8)
    rng = jax.random.PRNGKey(12)
    kq, kk, kv = jax.random.split(rng, 3)
    B, T, H, D = 2, 64, 4, 8
    q = jax.random.normal(kq, (B, T, H, D))
    k = jax.random.normal(kk, (B, T, H, D))
    v = jax.random.normal(kv, (B, T, H, D))
    ref = _full_attention(q, k, v, causal=True)
    out = pp.ring_self_attention(mesh, q, k, v, causal=True,
                                 layout="contiguous")
    np.testing.assert_allclose(jax.device_get(out), jax.device_get(ref),
                               rtol=2e-4, atol=2e-4)


def test_zigzag_order_and_work_balance():
    """The zigzag layout's accounting: the order is a permutation placing
    chunks (d, 2n-1-d) on device d; total attended pairs across all
    devices/steps equal the full causal count (exactness has no slack),
    and per-step work is balanced (max/min < 1.2) — vs the contiguous
    layout where future steps do a full block then discard it (~(n-1)/2n
    of FLOPs wasted)."""
    from paddle_tpu.parallel.ring_attention import (_zigzag_step_pairs,
                                                    zigzag_inverse,
                                                    zigzag_order)

    n, T = 8, 128
    c = T // (2 * n)
    order = np.asarray(zigzag_order(T, n))
    inv = np.asarray(zigzag_inverse(T, n))
    assert sorted(order.tolist()) == list(range(T))        # permutation
    np.testing.assert_array_equal(order[inv], np.arange(T))
    d = 3
    local = order[d * 2 * c:(d + 1) * 2 * c]
    assert local.tolist() == (list(range(d * c, (d + 1) * c)) +
                              list(range((2 * n - 1 - d) * c,
                                         (2 * n - d) * c)))

    diag, off = _zigzag_step_pairs(c)
    # every device does one diagonal step + (n-1) half-block steps
    total = n * (diag + (n - 1) * off)
    full_causal_pairs = T * (T + 1) // 2
    assert total == full_causal_pairs                      # zero waste
    assert max(diag, off) / min(diag, off) < 1.2           # balanced
    # contiguous layout: EVERY ring step runs a full-block kernel and
    # future blocks are discarded after the fact -> n^2 full blocks of
    # kernel FLOPs for T^2/2 useful pairs, ~2x waste
    T_local = T // n
    contiguous_kernel_pairs = n * n * T_local * T_local
    assert contiguous_kernel_pairs > 1.9 * full_causal_pairs
    # zigzag kernel work ~= useful work: only the diagonal step's masked
    # triangle is slack, a 1/(n+...) sliver that vanishes with n (1.12 at
    # n=8) — vs the contiguous layout's constant ~2x
    zz_kernel_pairs = n * (4 * c * c + (n - 1) * 2 * c * c)
    assert zz_kernel_pairs < 1.2 * full_causal_pairs
