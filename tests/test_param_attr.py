"""ParamAttr (trainer_config_helpers/attrs.py:52 ParameterAttribute +
python/paddle/v2/attr.py facade): name-based weight sharing, per-param
init/static/lr/l2 — lowered to fluid per-variable settings."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.v2 as paddle
from paddle_tpu.v2.attr import ExtraAttr, ParamAttr


@pytest.fixture(autouse=True)
def fresh_programs():
    fluid.reset_default_programs()
    yield


RS = np.random.RandomState(0)


def _n_params():
    return len(fluid.default_main_program().global_block().all_parameters())


def test_name_sharing_two_fc_same_weight():
    """Two fc layers under one ParamAttr name use ONE parameter (the
    reference's name-based sharing); gradients from both uses accumulate."""
    x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
    shared = ParamAttr(name="w_shared")
    h1 = paddle.layer.fc(x, 4, param_attr=shared, bias_attr=False)
    h2 = paddle.layer.fc(h1, 4, param_attr=shared, bias_attr=False)
    cost = paddle.layer.mse_cost(h2, x)
    params = [p.name
              for p in fluid.default_main_program().global_block()
              .all_parameters()]
    assert params.count("w_shared") == 1
    assert len(params) == 1            # no second fc weight was created

    opt = fluid.optimizer.SGDOptimizer(0.1)
    opt.minimize(cost.var)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xs = RS.randn(8, 4).astype(np.float32)
    before = np.asarray(exe.run(feed={"x": xs}, fetch_list=["w_shared"])[0])
    losses = [float(exe.run(feed={"x": xs}, fetch_list=[cost.var])[0])
              for _ in range(30)]
    after = np.asarray(exe.run(feed={"x": xs}, fetch_list=["w_shared"])[0])
    assert losses[-1] < losses[0]
    assert not np.allclose(before, after)


def test_shared_name_shape_mismatch_raises():
    x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
    shared = ParamAttr(name="w_shared")
    paddle.layer.fc(x, 4, param_attr=shared, bias_attr=False)
    with pytest.raises(ValueError, match="shape mismatch"):
        paddle.layer.fc(x, 8, param_attr=shared, bias_attr=False)


def test_is_static_freezes_parameter():
    """is_static=True (ParameterAttribute.is_static): parameter takes no
    updates while the rest of the net trains."""
    x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
    h = paddle.layer.fc(x, 8, act="tanh",
                        param_attr=ParamAttr(name="frozen", is_static=True),
                        bias_attr=False)
    out = paddle.layer.fc(h, 4)
    cost = paddle.layer.mse_cost(out, x)
    opt = fluid.optimizer.SGDOptimizer(0.1)
    opt.minimize(cost.var)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xs = RS.randn(8, 4).astype(np.float32)
    before = np.asarray(exe.run(feed={"x": xs}, fetch_list=["frozen"])[0])
    l0 = float(exe.run(feed={"x": xs}, fetch_list=[cost.var])[0])
    for _ in range(20):
        le = float(exe.run(feed={"x": xs}, fetch_list=[cost.var])[0])
    after = np.asarray(exe.run(feed={"x": xs}, fetch_list=["frozen"])[0])
    np.testing.assert_array_equal(before, after)   # frozen
    assert le < l0                                  # the rest still learns


def test_per_param_learning_rate_scale():
    """learning_rate=N multiplies the effective lr for that parameter only
    — exact under plain SGD: w' = w - (lr*N)*g."""
    x = paddle.layer.data("x", paddle.data_type.dense_vector(3))
    slow = paddle.layer.fc(x, 3, bias_attr=False,
                           param_attr=ParamAttr(name="w_slow",
                                                learning_rate=0.5))
    fast = paddle.layer.fc(x, 3, bias_attr=False,
                           param_attr=ParamAttr(name="w_fast",
                                                learning_rate=2.0))
    cost = paddle.layer.mse_cost(paddle.layer.addto_layer([slow, fast]), x)
    opt = fluid.optimizer.SGDOptimizer(0.1)
    opt.minimize(cost.var)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xs = RS.randn(4, 3).astype(np.float32)
    w_s0 = np.asarray(exe.scope.get("w_slow"))
    w_f0 = np.asarray(exe.scope.get("w_fast"))
    exe.run(feed={"x": xs}, fetch_list=[cost.var])
    w_s1 = np.asarray(exe.scope.get("w_slow"))
    w_f1 = np.asarray(exe.scope.get("w_fast"))
    # same gradient flows to both (summed outputs): step ratio == lr ratio
    ds, df = w_s1 - w_s0, w_f1 - w_f0
    np.testing.assert_allclose(df, ds * 4.0, rtol=1e-4, atol=1e-6)


def test_per_param_l2_rate_decays_weight():
    """l2_rate decays ONLY the attributed parameter (grad += l2*w)."""
    x = paddle.layer.data("x", paddle.data_type.dense_vector(3))
    # output does not depend on w_decay's direction in any data-driven way:
    # feed zeros so the data gradient is exactly 0 and ONLY decay moves it
    wd = paddle.layer.fc(x, 3, bias_attr=False,
                         param_attr=ParamAttr(name="w_decay", l2_rate=0.5))
    plain = paddle.layer.fc(x, 3, bias_attr=False,
                            param_attr=ParamAttr(name="w_plain"))
    cost = paddle.layer.mse_cost(paddle.layer.addto_layer([wd, plain]), x)
    opt = fluid.optimizer.SGDOptimizer(0.1)
    opt.minimize(cost.var)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    zeros = np.zeros((4, 3), np.float32)
    # read from the scope (every exe.run of the main program IS a step)
    w_d0 = np.asarray(exe.scope.get("w_decay"))
    w_p0 = np.asarray(exe.scope.get("w_plain"))
    exe.run(feed={"x": zeros}, fetch_list=[cost.var])
    w_d1 = np.asarray(exe.scope.get("w_decay"))
    w_p1 = np.asarray(exe.scope.get("w_plain"))
    np.testing.assert_array_equal(w_p1, w_p0)          # no decay, no grad
    np.testing.assert_allclose(w_d1, w_d0 * (1 - 0.1 * 0.5), rtol=1e-5)


def test_initial_std_and_uniform_init():
    x = paddle.layer.data("x", paddle.data_type.dense_vector(64))
    paddle.layer.fc(x, 256, bias_attr=False,
                    param_attr=ParamAttr(name="w_n", initial_mean=1.0,
                                         initial_std=0.01))
    paddle.layer.fc(x, 256, bias_attr=False,
                    param_attr=ParamAttr(name="w_u", initial_min=0.2,
                                         initial_max=0.4))
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    w_n = np.asarray(exe.scope.get("w_n"))
    w_u = np.asarray(exe.scope.get("w_u"))
    assert abs(w_n.mean() - 1.0) < 0.01 and w_n.std() < 0.05
    assert w_u.min() >= 0.2 and w_u.max() <= 0.4


def test_extra_attr_drop_rate_applies_dropout():
    x = paddle.layer.data("x", paddle.data_type.dense_vector(8))
    out = paddle.layer.fc(x, 8, layer_attr=ExtraAttr(drop_rate=0.5))
    ops = [op.type
           for op in fluid.default_main_program().global_block().ops]
    assert "dropout" in ops
    assert out.var.shape[-1] == 8


def test_param_attr_survives_program_serialization():
    """lr_scale/l2_rate ride Program JSON (golden-config discipline)."""
    x = paddle.layer.data("x", paddle.data_type.dense_vector(3))
    paddle.layer.fc(x, 3, bias_attr=False,
                    param_attr=ParamAttr(name="w", learning_rate=2.0,
                                         l2_rate=0.25))
    prog = fluid.default_main_program()
    clone = fluid.Program.from_dict(prog.to_dict())
    v = clone.global_block().var("w")
    assert v.lr_scale == 2.0 and v.l2_rate == 0.25


def test_generated_input_shares_training_embedding_by_name():
    """GeneratedInput(embedding_param=ParamAttr(name=...)) reuses the
    training-time trg-embedding table in the generation sub-model — the
    reference's train-config/gen-config weight-sharing workflow."""
    from paddle_tpu.fluid import layers as FL
    from paddle_tpu.nn import initializer as I
    from paddle_tpu.v2.layer import (GeneratedInput, LayerOutput, StaticInput,
                                     beam_search, memory)
    L = paddle.layer
    V_src, V, E, H = 8, 6, 5, 7
    src = L.data("src", paddle.data_type.integer_value_sequence(V_src))
    src_emb = L.embedding(src, E)
    enc = L.grumemory(src_emb, H)
    enc_last = L.last_seq(enc)
    # per-step projection (matmul keeps the time dim; fc would flatten)
    w = FL._create_parameter("enc_proj_w", (H, H), "float32",
                             I.uniform(-0.1, 0.1))
    proj = LayerOutput(FL.matmul(enc.var, w), enc.lengths)

    trg = L.data("trg", paddle.data_type.integer_value_sequence(V))
    trg_emb = L.embedding(trg, E, param_attr=ParamAttr(name="trg_embed"))
    assert trg_emb is not None
    n_before = _n_params()

    def gstep(y_t, enc_s, proj_s):
        dec_mem = memory("dec_state", H, boot_layer=enc_last)
        ctx = paddle.networks.simple_attention(enc_s, proj_s, dec_mem)
        h = L.fc([y_t, ctx, dec_mem], H, act="tanh", name="dec_state")
        return L.fc(h, V, act="softmax")

    tokens, scores = beam_search(
        gstep,
        [GeneratedInput(V, E, embedding_param=ParamAttr(name="trg_embed")),
         StaticInput(enc), StaticInput(proj)],
        bos_id=0, eos_id=1, beam_size=2, max_length=4)
    names = [p.name for p in fluid.default_main_program().global_block()
             .all_parameters()]
    assert names.count("trg_embed") == 1       # shared, not duplicated
    assert not any(n.startswith("gen_embed_w") for n in names)

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    srcs = RS.randint(0, V_src, (2, 5)).astype(np.int32)
    trgs = RS.randint(0, V, (2, 3)).astype(np.int32)
    t, s = exe.run(feed={"src": srcs,
                         "src__len__": np.full((2,), 5, np.int32),
                         "trg": trgs,
                         "trg__len__": np.full((2,), 3, np.int32)},
                   fetch_list=[tokens, scores])
    assert np.asarray(t).shape == (2, 2, 4)


def test_machine_translation_example_builds_and_steps():
    """The seqToseq demo (examples/machine_translation.py): train branch and
    shared-weight generation branch coexist in one program; a few steps run
    and the beam decodes well-formed output. (Full convergence to 100%
    unseen-source accuracy is demonstrated by running the example itself.)"""
    import importlib
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    mt = importlib.import_module("examples.machine_translation")

    loss, tokens, scores = mt.build()
    fluid.AdamOptimizer(0.02).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    lens_s = np.full((mt.B,), mt.TS, np.int32)
    lens_t = np.full((mt.B,), mt.TT, np.int32)
    losses = []
    for _ in range(8):
        srcs, trgs, nxts = mt.sample_batch(rng)
        losses.append(float(np.asarray(exe.run(
            feed={"src": srcs, "src__len__": lens_s, "trg": trgs,
                  "trg__len__": lens_t, "nxt": nxts},
            fetch_list=[loss])[0])))
    assert losses[-1] < losses[0]
    srcs, trgs, nxts = mt.sample_batch(rng, n=4)
    t, s = exe.run(feed={"src": srcs,
                         "src__len__": np.full((4,), mt.TS, np.int32),
                         "trg": trgs,
                         "trg__len__": np.full((4,), mt.TT, np.int32),
                         "nxt": nxts},
                   fetch_list=[tokens, scores])
    assert np.asarray(t).shape == (4, 4, mt.TT)
    assert (np.diff(np.asarray(s), axis=1) <= 1e-6).all()


def test_multi_part_fc_rejects_single_named_attr():
    """A named ParamAttr names ONE matrix; fc with a sparse + dense input
    pair must refuse it instead of sharing/clashing across parts."""
    from paddle_tpu.v2.data_type import sparse_binary_vector
    xs = paddle.layer.data("xs", sparse_binary_vector(100))
    xd = paddle.layer.data("xd", paddle.data_type.dense_vector(20))
    with pytest.raises(ValueError, match="multiple weight-bearing"):
        paddle.layer.fc([xs, xd], 8, param_attr=ParamAttr(name="w"))


def test_shared_reuse_conflicting_attrs_raise():
    x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
    paddle.layer.fc(x, 4, param_attr=ParamAttr(name="w"), bias_attr=False)
    with pytest.raises(ValueError, match="conflicting 'l2_rate'"):
        paddle.layer.fc(x, 4, bias_attr=False,
                        param_attr=ParamAttr(name="w", l2_rate=0.1))
    with pytest.raises(ValueError, match="conflicting 'is_static'"):
        paddle.layer.fc(x, 4, bias_attr=False,
                        param_attr=ParamAttr(name="w", is_static=True))


def test_per_param_l2_replaces_global_regularization():
    """ParamAttr(l2_rate=R) OVERRIDES the global regularizer for that
    parameter (no double decay)."""
    x = paddle.layer.data("x", paddle.data_type.dense_vector(3))
    wd = paddle.layer.fc(x, 3, bias_attr=False,
                         param_attr=ParamAttr(name="w_own", l2_rate=0.5))
    plain = paddle.layer.fc(x, 3, bias_attr=False,
                            param_attr=ParamAttr(name="w_glob"))
    cost = paddle.layer.mse_cost(paddle.layer.addto_layer([wd, plain]), x)
    opt = fluid.optimizer.SGDOptimizer(0.1)
    opt.minimize(cost.var, regularization=fluid.regularizer.L2Decay(0.2))
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    zeros = np.zeros((4, 3), np.float32)
    w_o0 = np.asarray(exe.scope.get("w_own"))
    w_g0 = np.asarray(exe.scope.get("w_glob"))
    exe.run(feed={"x": zeros}, fetch_list=[cost.var])
    w_o1 = np.asarray(exe.scope.get("w_own"))
    w_g1 = np.asarray(exe.scope.get("w_glob"))
    # own rate 0.5 (NOT 0.5+0.2); global param gets the global 0.2
    np.testing.assert_allclose(w_o1, w_o0 * (1 - 0.1 * 0.5), rtol=1e-5)
    np.testing.assert_allclose(w_g1, w_g0 * (1 - 0.1 * 0.2), rtol=1e-5)
