"""Parameter-update hooks (parameter/ParameterUpdaterHook.cpp): static
(frozen) parameters and magnitude pruning masks composed into the jitted
optimizer update."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import nn
from paddle_tpu.optimizer import SGD, Adam, HookSet, PruningHook, StaticHook


class _Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.embed = nn.Linear(8, 16)
        self.head = nn.Linear(16, 2)

    def __call__(self, params, x, **kw):
        return self.head(params["head"], self.embed(params["embed"], x))


def _loss(model):
    def loss(params, x, y):
        logp = jax.nn.log_softmax(model(params, x))
        return -jnp.take_along_axis(logp, y[:, None], 1).mean()
    return loss


def _data():
    rs = np.random.RandomState(0)
    return (jnp.asarray(rs.randn(32, 8), jnp.float32),
            jnp.asarray(rs.randint(0, 2, 32), jnp.int32))


def test_static_hook_freezes_matching_params():
    model = _Net()
    params = model.init(jax.random.PRNGKey(0))
    opt = Adam(5e-2, hooks=HookSet([(r"embed/", StaticHook())]))
    state = opt.init(params)
    x, y = _data()
    loss = _loss(model)

    @jax.jit
    def step(p, s):
        _, g = jax.value_and_grad(loss)(p, x, y)
        return opt.update(g, s, p)

    p = params
    for _ in range(5):
        p, state = step(p, state)
    np.testing.assert_array_equal(np.asarray(p["embed"]["w"]),
                                  np.asarray(params["embed"]["w"]))
    np.testing.assert_array_equal(np.asarray(p["embed"]["b"]),
                                  np.asarray(params["embed"]["b"]))
    assert not np.allclose(np.asarray(p["head"]["w"]),
                           np.asarray(params["head"]["w"]))


def test_pruning_hook_keeps_mask_through_training():
    model = _Net()
    params = model.init(jax.random.PRNGKey(1))
    opt = SGD(0.1, hooks=HookSet([(r"head/w$", PruningHook(0.5))]))
    state = opt.init(params)
    mask = np.asarray(state["hooks"]["head"]["w"]["mask"])
    kept = mask.sum() / mask.size
    assert 0.3 < kept <= 0.5 + 1e-6       # ~half pruned
    x, y = _data()
    loss = _loss(model)

    @jax.jit
    def step(p, s):
        _, g = jax.value_and_grad(loss)(p, x, y)
        return opt.update(g, s, p)

    p = params
    for _ in range(10):
        p, state = step(p, state)
    w = np.asarray(p["head"]["w"])
    # pruned entries stay exactly zero; surviving entries train
    np.testing.assert_array_equal(w[mask == 0], 0.0)
    assert not np.allclose(w[mask == 1],
                           np.asarray(params["head"]["w"])[mask == 1])


def test_hooks_survive_checkpoint_roundtrip(tmp_path):
    import io

    from paddle_tpu.trainer import from_tar, to_tar
    model = _Net()
    params = model.init(jax.random.PRNGKey(2))
    opt = SGD(0.1, hooks=HookSet([(r"head/w$", PruningHook(0.5))]))
    state = opt.init(params)
    buf = io.BytesIO()
    to_tar(buf, state)
    buf.seek(0)
    back = from_tar(buf)
    np.testing.assert_array_equal(
        np.asarray(back["hooks"]["head"]["w"]["mask"]),
        np.asarray(state["hooks"]["head"]["w"]["mask"]))
