"""@provider decorator (PyDataProvider2.py:55 protocol) and Topology
(v2/topology.py:27) facades."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.v2 as paddle
from paddle_tpu.data.provider import CacheType, provider


@pytest.fixture(autouse=True)
def fresh_programs():
    fluid.reset_default_programs()
    yield


def test_provider_decorator_basic():
    calls = []

    def init_hook(settings, vocab=None):
        settings.vocab = vocab
        calls.append("init")

    @provider(input_types=[paddle.data_type.dense_vector(3),
                           paddle.data_type.integer_value(2)],
              init_hook=init_hook, vocab=7)
    def process(settings, src):
        assert settings.vocab == 7
        for i in range(4):
            yield np.full((3,), float(i), np.float32), i % 2

    reader = process("fileA")
    assert calls == ["init"]                     # once, before rows
    rows = list(reader())
    assert len(rows) == 4 and rows[2][1] == 0
    assert len(reader.settings.input_types) == 2


def test_provider_multiple_sources_and_cache():
    loads = []

    @provider(cache=CacheType.CACHE_PASS_IN_MEM)
    def process(settings, src):
        loads.append(src)
        for i in range(2):
            yield (src, i)

    reader = process("a", "b")
    p1 = list(reader())
    p2 = list(reader())                          # served from the cache
    assert p1 == p2 == [("a", 0), ("a", 1), ("b", 0), ("b", 1)]
    assert loads == ["a", "b"]                   # each source read ONCE


def test_provider_shuffle_covers_all_rows():
    @provider(should_shuffle=True)
    def process(settings, src):
        yield from range(20)

    rows = list(process()())
    assert sorted(rows) == list(range(20))


def test_provider_feeds_trainer():
    """The ported-provider workflow end to end: decorated generator ->
    reader creator -> batch -> SGD.train."""
    @provider(input_types=[paddle.data_type.dense_vector(4),
                           paddle.data_type.dense_vector(1)])
    def process(settings, src):
        rs = np.random.RandomState(0)
        for _ in range(64):
            x = rs.randn(4).astype(np.float32)
            yield x, np.array([x.sum()], np.float32)

    x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
    y = paddle.layer.data("y", paddle.data_type.dense_vector(1))
    cost = paddle.layer.square_error_cost(paddle.layer.fc(x, 1), y)
    t = paddle.SGD(cost, paddle.optimizer.SGD(0.1))
    costs = []
    t.train(paddle.batch(process("train.txt"), 16), num_passes=3,
            feeding=[x, y],
            event_handler=lambda e: costs.append(e.cost)
            if hasattr(e, "cost") else None)
    assert costs[-1] < costs[0]


def test_topology_proto_and_data_type():
    x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
    y = paddle.layer.data("y", paddle.data_type.integer_value(3))
    logits = paddle.layer.fc(x, 3)
    cost = paddle.layer.classification_cost(logits, y)
    topo = paddle.Topology(cost)
    d = topo.proto()
    assert d["blocks"][0]["ops"]
    names = [n for n, _ in topo.data_type()]
    assert "x" in names and "y" in names
    assert topo.get_layer_proto("x")["is_data"]
    assert topo.get_layer_proto("no_such") is None
    # round trip through the serialized form
    clone = fluid.Program.from_dict(__import__("json").loads(topo.serialize()))
    assert [o.type for o in clone.global_block().ops] == \
           [o.type for o in topo.program.global_block().ops]


def test_topology_serialize_for_inference_prunes_cost():
    x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
    y = paddle.layer.data("y", paddle.data_type.integer_value(3))
    logits = paddle.layer.fc(x, 3)
    cost = paddle.layer.classification_cost(logits, y)
    topo = paddle.Topology(cost)
    d = topo.serialize_for_inference([logits])
    types = [op["type"] for blk in d["blocks"] for op in blk["ops"]]
    assert "cross_entropy" not in types and "mul" in types


def test_topology_rejects_non_layer():
    with pytest.raises(ValueError, match="LayerOutput"):
        paddle.Topology(42)
