"""recurrent_group / memory / StaticInput / beam generation DSL tests.

The round-1 verdict's #3 gap: the reference's signature capability
(trainer_config_helpers/layers.py:3939 recurrent_group + memory + StaticInput,
RecurrentGradientMachine generation :964/:1020). Acceptance here mirrors the
verdict's "done" bar: the v2 DSL expresses the seq2seq encoder-decoder-attention
demo without models/seq2seq.py, and generation decodes deterministic outputs.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers as FL
from paddle_tpu.nn import initializer as I
from paddle_tpu.v2 import layer as L
from paddle_tpu.v2 import networks as NW
from paddle_tpu.v2.data_type import (dense_vector_sequence,
                                     integer_value_sequence)
from paddle_tpu.v2.layer import (GeneratedInput, LayerOutput, StaticInput,
                                 beam_search, memory, recurrent_group)


@pytest.fixture(autouse=True)
def fresh_programs():
    fluid.reset_default_programs()
    fluid.executor._global_scope = fluid.Scope()
    yield


def _startup(exe):
    exe.run(fluid.default_startup_program())


def test_recurrent_group_simple_rnn_trains():
    """A tanh-RNN composed in the step fn (memory + fc name binding)."""
    B, T, D, H = 4, 5, 3, 8
    x = L.data("x", dense_vector_sequence(D))
    y = FL.data("y", shape=(), dtype="int64")

    def step(x_t):
        mem = memory("state", H)
        h = L.fc([x_t, mem], H, act="tanh", name="state")
        return h

    out = recurrent_group(step, x)
    last = L.last_seq(out)
    logits = FL.fc(last.var, 2)
    loss = FL.mean(FL.softmax_with_cross_entropy(logits, y))
    fluid.AdamOptimizer(0.05).minimize(loss)

    exe = fluid.Executor()
    _startup(exe)
    rng = np.random.RandomState(0)
    xs = rng.randn(B, T, D).astype(np.float32)
    ys = (xs.sum(axis=(1, 2)) > 0).astype(np.int64)
    lens = np.full((B,), T, np.int32)
    feed = {"x": xs, "x__len__": lens, "y": ys}
    losses = [float(exe.run(feed=feed, fetch_list=[loss])[0])
              for _ in range(30)]
    assert losses[-1] < losses[0] * 0.8


def test_recurrent_group_matches_cumsum():
    """memory accumulation semantics: h_t = h_{t-1} + x_t via identity()."""
    B, T, D = 2, 4, 3
    x = L.data("x", dense_vector_sequence(D))

    def step(x_t):
        mem = memory("acc", D)
        s = LayerOutput(FL.elementwise_add(mem.var, x_t.var))
        L.identity(s, name="acc")
        return s

    out = recurrent_group(step, x)
    exe = fluid.Executor()
    xs = np.random.RandomState(0).randn(B, T, D).astype(np.float32)
    res, = exe.run(feed={"x": xs, "x__len__": np.full((B,), T, np.int32)},
                   fetch_list=[out.var])
    np.testing.assert_allclose(res, np.cumsum(xs, axis=1), rtol=1e-5)


def _encoder(src, vocab, E, H):
    emb = L.embedding(src, E)
    enc = L.grumemory(emb, H)
    # per-step projection to the attention space: matmul keeps the time dim
    # (fc would flatten [B, T, H] -> [B, T*H])
    w = FL._create_parameter("enc_proj_w", (H, H), "float32",
                             I.uniform(-0.1, 0.1))
    proj = LayerOutput(FL.matmul(enc.var, w), enc.lengths)
    last = L.last_seq(enc)
    return enc, proj, last


def test_seq2seq_attention_via_dsl_trains():
    """Encoder-decoder with attention expressed ONLY through the DSL
    (recurrent_group + StaticInput + simple_attention), no models/seq2seq.py."""
    B, Ts, Tt = 4, 5, 4
    V_src, V_trg, E, H = 12, 10, 6, 8
    src = L.data("src", integer_value_sequence(V_src))
    trg = L.data("trg", integer_value_sequence(V_trg))
    nxt = FL.data("nxt", shape=(Tt,), dtype="int64")

    enc, proj, enc_last = _encoder(src, V_src, E, H)
    trg_emb = L.embedding(trg, E)

    def step(y_t, enc_s, proj_s):
        dec_mem = memory("dec_state", H, boot_layer=enc_last)
        context = NW.simple_attention(enc_s, proj_s, dec_mem)
        h = L.fc([y_t, context, dec_mem], H, act="tanh", name="dec_state")
        return L.fc(h, V_trg, act="softmax")

    dec = recurrent_group(step,
                          [trg_emb, StaticInput(enc), StaticInput(proj)])
    probs2d = FL.reshape(dec.var, (-1, V_trg))
    labels1d = FL.reshape(nxt, (-1,))
    loss = FL.mean(FL.cross_entropy(probs2d, labels1d))
    fluid.AdamOptimizer(0.1).minimize(loss)

    exe = fluid.Executor()
    _startup(exe)
    rng = np.random.RandomState(0)
    srcs = rng.randint(0, V_src, (B, Ts)).astype(np.int32)
    # learnable mapping: target token = (src first token + t) % V_trg
    trgs = np.zeros((B, Tt), np.int32)
    nxts = np.zeros((B, Tt), np.int64)
    for b in range(B):
        for t in range(Tt):
            nxts[b, t] = (srcs[b, 0] + t) % V_trg
            trgs[b, t] = nxts[b, t - 1] if t else 0
    feed = {"src": srcs, "src__len__": np.full((B,), Ts, np.int32),
            "trg": trgs, "trg__len__": np.full((B,), Tt, np.int32),
            "nxt": nxts}
    losses = [float(exe.run(feed=feed, fetch_list=[loss])[0])
              for _ in range(40)]
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_beam_generation_deterministic_and_wellformed():
    """Generation regression (RecurrentGradientMachine::beamSearch analog):
    deterministic decode, best-first scores, EOS-sticky suffixes."""
    B, Ts = 3, 5
    V_src, V, E, H = 12, 7, 6, 8
    BOS, EOS, K, MAXLEN = 0, 1, 3, 6
    src = L.data("src", integer_value_sequence(V_src))
    enc, proj, enc_last = _encoder(src, V_src, E, H)

    def gstep(y_t, enc_s, proj_s):
        dec_mem = memory("dec_state", H, boot_layer=enc_last)
        context = NW.simple_attention(enc_s, proj_s, dec_mem)
        h = L.fc([y_t, context, dec_mem], H, act="tanh", name="dec_state")
        return L.fc(h, V, act="softmax")

    tokens, scores = beam_search(
        gstep, [GeneratedInput(V, E), StaticInput(enc), StaticInput(proj)],
        bos_id=BOS, eos_id=EOS, beam_size=K, max_length=MAXLEN)

    exe = fluid.Executor()
    _startup(exe)
    rng = np.random.RandomState(3)
    srcs = rng.randint(0, V_src, (B, Ts)).astype(np.int32)
    feed = {"src": srcs, "src__len__": np.full((B,), Ts, np.int32)}
    t1, s1 = exe.run(feed=feed, fetch_list=[tokens, scores])
    t2, s2 = exe.run(feed=feed, fetch_list=[tokens, scores])
    np.testing.assert_array_equal(t1, t2)          # deterministic
    np.testing.assert_array_equal(s1, s2)
    assert t1.shape == (B, K, MAXLEN) and s1.shape == (B, K)
    assert (t1 >= 0).all() and (t1 < V).all()
    assert (np.diff(s1, axis=1) <= 1e-6).all()     # best-first ordering
    # EOS is sticky: everything after the first EOS is EOS
    for b in range(B):
        for k in range(K):
            seq = t1[b, k]
            hit = np.where(seq == EOS)[0]
            if hit.size:
                assert (seq[hit[0]:] == EOS).all()
    # the decode consults the step net: perturbing its weights changes it
    # (untrained tiny nets can argmax identically across sources, so a
    # source-change check would be too weak)
    prng = np.random.RandomState(1)
    for n in list(exe.scope.vars):
        v = np.asarray(exe.scope.get(n))
        if v.dtype == np.float32 and v.ndim >= 1:
            exe.scope.set(n, v + 0.7 * prng.standard_normal(v.shape)
                          .astype(np.float32))
    t3, _ = exe.run(feed=feed, fetch_list=[tokens, scores])
    assert not np.array_equal(t1, t3)


def test_beam_generation_with_registered_constraint():
    """End-to-end BeamSearchControlCallbacks analog
    (RecurrentGradientMachine.h:106-123): a registered logits-mask hook
    drives the decode through the v2 DSL — a forbidden token family never
    appears, and a min-length rule delays EOS, exactly the kind of
    vocabulary control the reference's per-step callbacks were used for."""
    import jax.numpy as jnp

    from paddle_tpu.ops.beam_search import CONSTRAINTS, register_constraint

    B, Ts = 3, 5
    V_src, V, E, H = 12, 9, 6, 8
    BOS, EOS, K, MAXLEN = 0, 1, 3, 6
    FORBIDDEN = (4, 5)      # a "token family" (e.g. digits)
    MIN_LEN = 3

    @register_constraint("no_45_minlen3")
    def _mask(logp, step):
        for tok in FORBIDDEN:
            logp = logp.at[..., tok].set(-1e9)
        # min-length: EOS is illegal before MIN_LEN steps have been emitted
        return jnp.where(step < MIN_LEN - 1,
                         logp.at[..., EOS].set(-1e9), logp)

    try:
        src = L.data("src", integer_value_sequence(V_src))
        enc, proj, enc_last = _encoder(src, V_src, E, H)

        def gstep(y_t, enc_s, proj_s):
            dec_mem = memory("dec_state", H, boot_layer=enc_last)
            context = NW.simple_attention(enc_s, proj_s, dec_mem)
            h = L.fc([y_t, context, dec_mem], H, act="tanh", name="dec_state")
            return L.fc(h, V, act="softmax")

        tokens, scores = beam_search(
            gstep, [GeneratedInput(V, E), StaticInput(enc), StaticInput(proj)],
            bos_id=BOS, eos_id=EOS, beam_size=K, max_length=MAXLEN,
            constraint="no_45_minlen3")

        exe = fluid.Executor()
        _startup(exe)
        rng = np.random.RandomState(3)
        srcs = rng.randint(0, V_src, (B, Ts)).astype(np.int32)
        feed = {"src": srcs, "src__len__": np.full((B,), Ts, np.int32)}
        t1, s1 = exe.run(feed=feed, fetch_list=[tokens, scores])
        assert t1.shape == (B, K, MAXLEN)
        for tok in FORBIDDEN:                      # family never emitted
            assert not np.any(t1 == tok)
        for b in range(B):                         # EOS delayed to MIN_LEN
            for k in range(K):
                assert not np.any(t1[b, k, : MIN_LEN - 1] == EOS)
    finally:
        CONSTRAINTS.pop("no_45_minlen3", None)


def test_beam_constraint_unregistered_name_is_loud():
    src = L.data("src", integer_value_sequence(8))
    enc, proj, enc_last = _encoder(src, 8, 4, 6)

    def gstep(y_t, enc_s, proj_s):
        dec_mem = memory("dec_state", 6, boot_layer=enc_last)
        context = NW.simple_attention(enc_s, proj_s, dec_mem)
        h = L.fc([y_t, context, dec_mem], 6, act="tanh", name="dec_state")
        return L.fc(h, 5, act="softmax")

    tokens, scores = beam_search(
        gstep, [GeneratedInput(5, 4), StaticInput(enc), StaticInput(proj)],
        bos_id=0, eos_id=1, beam_size=2, max_length=4,
        constraint="never_registered")
    exe = fluid.Executor()
    _startup(exe)
    feed = {"src": np.zeros((2, 3), np.int32),
            "src__len__": np.full((2,), 3, np.int32)}
    with pytest.raises(KeyError, match="never_registered"):
        exe.run(feed=feed, fetch_list=[tokens, scores])
