"""Registry-wide op sweep: every registered op gets at least an execution
spec, and differentiable ops get a numeric-gradient check.

This is the parametrized analog of the reference's per-op test files
(python/paddle/v2/fluid/tests/test_*_op.py, ~100 files driven by op_test.py's
get_numeric_gradient, and gserver/tests/test_LayerGrad.cpp). The key gate:
``test_every_registered_op_is_covered`` FAILS when a new op is registered
without a spec here, so registry growth stays test-gated.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from op_test import check_grad
from paddle_tpu.fluid.registry import OpRegistry

R = np.random.RandomState(7)


def f32(*shape):
    return R.randn(*shape).astype(np.float32)


def pos32(*shape):
    return (R.rand(*shape).astype(np.float32) + 0.1)


B, T, D, N, V, H = 2, 4, 3, 3, 8, 3
LENGTHS = np.array([4, 2], np.int32)

# Each spec: inputs dict, attrs dict, optional:
#   grad: list of (slot, index) float inputs to numeric-grad check
#   out: output slot to scalarize for grad (default: first returned)
SPECS = {
    # -- basic math ----------------------------------------------------------
    "elementwise_add": dict(ins={"X": [f32(B, D)], "Y": [f32(B, D)]},
                            grad=[("X", 0), ("Y", 0)]),
    "elementwise_sub": dict(ins={"X": [f32(B, D)], "Y": [f32(B, D)]},
                            grad=[("X", 0)]),
    "elementwise_mul": dict(ins={"X": [f32(B, D)], "Y": [f32(B, D)]},
                            grad=[("X", 0), ("Y", 0)]),
    "elementwise_div": dict(ins={"X": [f32(B, D)], "Y": [pos32(B, D)]},
                            grad=[("X", 0)]),
    "mul": dict(ins={"X": [f32(B, D)], "Y": [f32(D, H)]},
                grad=[("X", 0), ("Y", 0)]),
    "matmul": dict(ins={"X": [f32(B, D)], "Y": [f32(D, H)]},
                   grad=[("X", 0), ("Y", 0)]),
    "scale": dict(ins={"X": [f32(B, D)]}, attrs={"scale": 2.0, "bias": 1.0},
                  grad=[("X", 0)]),
    "mean": dict(ins={"X": [f32(B, D)]}, grad=[("X", 0)]),
    "sum": dict(ins={"X": [f32(B, D), f32(B, D)]}, grad=[("X", 0), ("X", 1)]),
    "minus": dict(ins={"X": [f32(B, D)], "Y": [f32(B, D)]}, grad=[("X", 0)]),
    "sign": dict(ins={"X": [f32(B, D)]}),
    "pow": dict(ins={"X": [pos32(B, D)]}, attrs={"factor": 2.0},
                grad=[("X", 0)]),
    "reduce_sum": dict(ins={"X": [f32(B, T, D)]}, attrs={"dim": 1},
                       grad=[("X", 0)]),
    "reduce_mean": dict(ins={"X": [f32(B, T, D)]}, attrs={"dim": 1},
                        grad=[("X", 0)]),
    "reduce_max": dict(ins={"X": [f32(B, T, D)]}, attrs={"dim": 1}),
    "reduce_min": dict(ins={"X": [f32(B, T, D)]}, attrs={"dim": 1}),
    "reshape": dict(ins={"X": [f32(B, T, D)]}, attrs={"shape": (B, T * D)},
                    grad=[("X", 0)]),
    "transpose": dict(ins={"X": [f32(B, T, D)]}, attrs={"axis": (1, 0, 2)},
                      grad=[("X", 0)]),
    "concat": dict(ins={"X": [f32(B, D), f32(B, D)]}, attrs={"axis": 1},
                   grad=[("X", 0)]),
    "split": dict(ins={"X": [f32(B, 6)]},
                  attrs={"num_or_sections": 2, "axis": 1}),
    "cast": dict(ins={"X": [f32(B, D)]}, attrs={"dtype": "float32"}),
    "clip": dict(ins={"X": [f32(B, D)]}, attrs={"min": -0.5, "max": 0.5}),
    "clip_by_norm": dict(ins={"X": [f32(B, D)]}, attrs={"max_norm": 1.0},
                         grad=[("X", 0)]),
    "expand": dict(ins={"X": [f32(B, 1, D)]},
                   attrs={"expand_times": (1, T, 1)}, grad=[("X", 0)]),
    "pad": dict(ins={"X": [f32(B, D)]},
                attrs={"paddings": ((0, 0), (1, 2)), "pad_value": 0.0},
                grad=[("X", 0)]),
    "crop": dict(ins={"X": [f32(B, 5)]},
                 attrs={"offsets": (0, 1), "shape": (B, 3)}, grad=[("X", 0)]),
    "gather": dict(ins={"X": [f32(V, D)],
                        "Index": [np.array([1, 3, 5], np.int32)]},
                   grad=[("X", 0)]),
    "scatter": dict(ins={"Ref": [f32(V, D)],
                         "Index": [np.array([1, 3], np.int32)],
                         "Updates": [f32(2, D)]}, grad=[("Ref", 0)]),
    "multiplex": dict(ins={"Ids": [np.array([0, 1], np.int32)],
                           "X": [f32(B, D), f32(B, D)]}, grad=[("X", 0)]),
    "l1_norm": dict(ins={"X": [f32(B, D)]}),
    "squared_l2_norm": dict(ins={"X": [f32(B, D)]}, grad=[("X", 0)]),
    "squared_l2_distance": dict(ins={"X": [f32(B, D)], "Y": [f32(B, D)]},
                                grad=[("X", 0)]),
    "cos_sim": dict(ins={"X": [f32(B, D)], "Y": [f32(B, D)]},
                    grad=[("X", 0), ("Y", 0)]),
    "l2_normalize": dict(ins={"X": [f32(B, D)]}, grad=[("X", 0)]),
    "prelu": dict(ins={"X": [f32(B, D)], "Alpha": [pos32(1)]},
                  grad=[("X", 0)]),
    "conv_shift": dict(ins={"X": [f32(B, 6)], "Y": [f32(B, 3)]},
                       grad=[("X", 0), ("Y", 0)]),
    "bilinear_tensor_product": dict(
        ins={"X": [f32(B, D)], "Y": [f32(B, D)], "Weight": [f32(2, D, D)],
             "Bias": [f32(2)]}, grad=[("X", 0), ("Weight", 0)]),
    "interpolation": dict(ins={"X": [f32(B, D)], "Y": [f32(B, D)],
                               "W": [pos32(B)]}, grad=[("X", 0)]),
    # -- fills / random / logic ---------------------------------------------
    "fill_constant": dict(ins={}, attrs={"shape": (B, D), "value": 1.5}),
    "fill_zeros_like": dict(ins={"X": [f32(B, D)]}),
    "fill_constant_batch_size_like": dict(
        ins={"Input": [f32(B, D)]},
        attrs={"shape": (1, 5), "value": 0.5}),
    "gaussian_random": dict(ins={}, attrs={"shape": (B, D), "seed": 1}),
    "uniform_random": dict(ins={}, attrs={"shape": (B, D), "seed": 1}),
    "dropout": dict(ins={"X": [f32(B, D)]},
                    attrs={"dropout_prob": 0.5, "is_test": True}),
    "assign": dict(ins={"X": [f32(B, D)]}),
    "increment": dict(ins={"X": [np.int32(3)]}, attrs={"step": 2}),
    "is_empty": dict(ins={"X": [f32(B, D)]}),
    "less_than": dict(ins={"X": [f32(B)], "Y": [f32(B)]}),
    "less_equal": dict(ins={"X": [f32(B)], "Y": [f32(B)]}),
    "greater_than": dict(ins={"X": [f32(B)], "Y": [f32(B)]}),
    "greater_equal": dict(ins={"X": [f32(B)], "Y": [f32(B)]}),
    "equal": dict(ins={"X": [f32(B)], "Y": [f32(B)]}),
    "not_equal": dict(ins={"X": [f32(B)], "Y": [f32(B)]}),
    "logical_and": dict(ins={"X": [np.array([True, False])],
                             "Y": [np.array([True, True])]}),
    "logical_or": dict(ins={"X": [np.array([True, False])],
                            "Y": [np.array([False, False])]}),
    "logical_not": dict(ins={"X": [np.array([True, False])]}),
    # -- arrays --------------------------------------------------------------
    "array_write": dict(ins={"X": [f32(D)], "I": [np.int32(1)]},
                        attrs={"capacity": 4}),
    "array_read": dict(ins={"Array": [f32(4, D)], "I": [np.int32(2)]}),
    "array_length": dict(ins={"Array": [f32(4, D)]}),
    "lod_tensor_to_array": dict(ins={"X": [f32(B, T, D)]}),
    "array_to_lod_tensor": dict(ins={"X": [f32(T, B, D)]}),
    "lod_reset": dict(ins={"X": [f32(B, T)], "Lengths": [LENGTHS]}),
    "squeeze": dict(ins={"X": [f32(B, T, 1)]}, attrs={"axis": -1},
                    grad=[("X", 0)]),
    "nested_seq_pool": dict(
        ins={"X": [f32(B, 2, T, D)],
             "SubLengths": [np.array([[3, 2], [1, 0]], np.int32)],
             "SeqLengths": [np.array([2, 1], np.int32)]},
        attrs={"pool_type": "max"}, grad=[("X", 0)]),
    "nested_last_step": dict(
        ins={"X": [f32(B, 2, T, D)],
             "SubLengths": [np.array([[3, 2], [1, 0]], np.int32)],
             "SeqLengths": [np.array([2, 1], np.int32)]}),
    "nested_lstm": dict(
        ins={"X": [f32(B, 2, T, D)],
             "SubLengths": [np.array([[3, 2], [1, 0]], np.int32)],
             "SeqLengths": [np.array([2, 1], np.int32)],
             "W": [f32(D, 4 * H)], "U": [f32(H, 4 * H)],
             "B": [f32(4 * H)]}, out="Out", grad=[("W", 0)]),
    "unsqueeze": dict(ins={"X": [f32(B, T)]}, attrs={"axis": -1},
                      grad=[("X", 0)]),
    # -- activations ---------------------------------------------------------
    **{a: dict(ins={"X": [f32(B, D)]}, grad=[("X", 0)])
       for a in ("sigmoid", "tanh", "gelu", "softsign", "square",
                 "softrelu", "stanh", "swish", "softmax", "log_softmax")},
    **{a: dict(ins={"X": [f32(B, D)]})  # kinked/discontinuous: no grad check
       for a in ("relu", "brelu", "leaky_relu", "elu", "abs", "abs_act",
                 "soft_shrink", "hard_shrink", "thresholded_relu",
                 "hard_sigmoid")},
    "sqrt": dict(ins={"X": [pos32(B, D)]}, grad=[("X", 0)]),
    "log": dict(ins={"X": [pos32(B, D)]}, grad=[("X", 0)]),
    "reciprocal": dict(ins={"X": [pos32(B, D)]}, grad=[("X", 0)]),
    "exponential": dict(ins={"X": [f32(B, D)]}, grad=[("X", 0)]),
    # -- embedding / conv / pool / norm --------------------------------------
    "lookup_table": dict(ins={"W": [f32(V, D)],
                              "Ids": [np.array([[1, 2], [3, 4]], np.int32)]},
                         grad=[("W", 0)]),
    "conv2d": dict(ins={"Input": [f32(B, 5, 5, 2)],
                        "Filter": [f32(3, 3, 2, 4)]},
                   grad=[("Input", 0), ("Filter", 0)]),
    "depthwise_conv2d": dict(ins={"Input": [f32(B, 5, 5, 2)],
                                  "Filter": [f32(3, 3, 1, 2)]},
                             grad=[("Filter", 0)]),
    "conv2d_transpose": dict(ins={"Input": [f32(B, 3, 3, 2)],
                                  "Filter": [f32(3, 3, 2, 4)]},
                             grad=[("Filter", 0)]),
    "conv3d": dict(ins={"Input": [f32(B, 4, 4, 4, 1)],
                        "Filter": [f32(2, 2, 2, 1, 2)]},
                   grad=[("Filter", 0)]),
    "pool2d": dict(ins={"X": [f32(B, 4, 4, 2)]}, attrs={"ksize": 2}),
    "pool3d": dict(ins={"X": [f32(B, 4, 4, 4, 1)]}, attrs={"ksize": 2}),
    "pool2d_with_index": dict(ins={"X": [f32(B, 4, 4, 2)]},
                              attrs={"ksize": 2}),
    "lrn": dict(ins={"X": [f32(B, 4, 4, 5)]}, grad=[("X", 0)]),
    "maxout": dict(ins={"X": [f32(B, 4, 4, 6)]}, attrs={"groups": 2}),
    "roi_pool": dict(ins={"X": [f32(8, 8, 2)],   # single image [H, W, C]
                          "ROIs": [np.array([[0, 0, 4, 4]], np.float32)]},
                     attrs={"pooled_height": 2, "pooled_width": 2}),
    "row_conv": dict(ins={"X": [f32(B, T, D)], "Filter": [f32(3, D)]},
                     grad=[("Filter", 0)]),
    "block_expand": dict(ins={"X": [f32(B, 4, 4, 2)]}, attrs={"block": 2}),
    "bilinear_interp": dict(ins={"X": [f32(B, 4, 4, 2)]},
                            attrs={"out_h": 8, "out_w": 8}, grad=[("X", 0)]),
    "spp": dict(ins={"X": [f32(B, 6, 6, 2)]}, attrs={"pyramid_height": 2}),
    "batch_norm": dict(ins={"X": [f32(B, T, 2)], "Scale": [pos32(2)],
                            "Bias": [f32(2)], "Mean": [f32(2) * 0],
                            "Variance": [pos32(2)]},
                       out="Y", grad=[("X", 0), ("Scale", 0), ("Bias", 0)]),
    "batch_norm_infer": dict(ins={"X": [f32(B, T, 2)], "Scale": [pos32(2)],
                                  "Bias": [f32(2)], "Mean": [f32(2) * 0],
                                  "Variance": [pos32(2)]}),
    "layer_norm": dict(ins={"X": [f32(B, D)], "Scale": [pos32(D)],
                            "Bias": [f32(D)]}, grad=[("X", 0), ("Scale", 0)]),
    # -- losses --------------------------------------------------------------
    "cross_entropy": dict(
        ins={"X": [np.abs(f32(B, N)) + 0.2], "Label": [np.array([0, 2])]},
        out="Y", grad=[("X", 0)]),
    "softmax_with_cross_entropy": dict(
        ins={"Logits": [f32(B, N)], "Label": [np.array([0, 2])]},
        out="Loss", grad=[("Logits", 0)]),
    "sigmoid_cross_entropy_with_logits": dict(
        ins={"X": [f32(B, N)], "Label": [R.rand(B, N).astype(np.float32)]},
        grad=[("X", 0)]),
    "square_error": dict(ins={"X": [f32(B, 1)], "Label": [f32(B, 1)]},
                         grad=[("X", 0)]),
    "smooth_l1_loss": dict(ins={"X": [f32(B, D)], "Label": [f32(B, D)]},
                           grad=[("X", 0)]),
    "huber_loss": dict(ins={"X": [f32(B, 1)], "Label": [f32(B, 1)]}),
    "modified_huber_loss": dict(
        ins={"X": [f32(B, 1)],
             "Label": [np.array([[1.0], [-1.0]], np.float32)]}),
    "hinge_loss": dict(ins={"X": [f32(B, 1)],
                            "Label": [np.array([[1.0], [-1.0]], np.float32)]}),
    "log_loss": dict(ins={"Predicted": [R.rand(B, 1).astype(np.float32) * 0.8
                                        + 0.1],
                          "Label": [np.array([[1.0], [0.0]], np.float32)]},
                     grad=[("Predicted", 0)]),
    "rank_loss": dict(ins={"Left": [f32(B, 1)], "Right": [f32(B, 1)],
                           "Label": [np.array([[1.0], [0.0]], np.float32)]},
                      grad=[("Left", 0)]),
    "margin_rank_loss": dict(
        ins={"X1": [f32(B, 1)], "X2": [f32(B, 1)],
             "Label": [np.array([[1.0], [-1.0]], np.float32)]},
        attrs={"margin": 0.1}),
    "multi_binary_label_cross_entropy": dict(
        ins={"X": [f32(B, N)],
             "Label": [R.randint(0, 2, (B, N)).astype(np.float32)]},
        grad=[("X", 0)]),
    "soft_binary_class_cross_entropy": dict(
        ins={"X": [R.rand(B, N).astype(np.float32) * 0.8 + 0.1],
             "Label": [R.rand(B, N).astype(np.float32)]}, grad=[("X", 0)]),
    "kldiv_loss": dict(
        ins={"X": [np.log(R.dirichlet(np.ones(N), B).astype(np.float32))],
             "Target": [R.dirichlet(np.ones(N), B).astype(np.float32)]}),
    # -- metrics -------------------------------------------------------------
    "accuracy": dict(ins={"Out": [f32(B, N)], "Label": [np.array([0, 2])]}),
    "top_k": dict(ins={"X": [f32(B, V)]}, attrs={"k": 3}),
    "auc": dict(ins={"Out": [R.rand(8).astype(np.float32)],
                     "Label": [R.randint(0, 2, 8).astype(np.int32)]}),
    "precision_recall": dict(
        ins={"Out": [R.randint(0, N, 8).astype(np.int32)],
             "Label": [R.randint(0, N, 8).astype(np.int32)]},
        attrs={"num_classes": N}),
    "chunk_eval": dict(
        ins={"Inference": [R.randint(0, 2, (B, T)).astype(np.int32)],
             "Label": [R.randint(0, 2, (B, T)).astype(np.int32)],
             "Lengths": [LENGTHS]}),
    "positive_negative_pair": dict(
        ins={"Score": [R.rand(6).astype(np.float32)],
             "Label": [R.randint(0, 3, 6).astype(np.float32)],
             "QueryID": [np.array([0, 0, 0, 1, 1, 1], np.int32)]}),
    # -- sequences -----------------------------------------------------------
    "sequence_pool": dict(ins={"X": [f32(B, T, D)], "Lengths": [LENGTHS]},
                          attrs={"pool_type": "average"}, grad=[("X", 0)]),
    "sequence_last_step": dict(ins={"X": [f32(B, T, D)],
                                    "Lengths": [LENGTHS]}),
    "sequence_first_step": dict(ins={"X": [f32(B, T, D)],
                                     "Lengths": [LENGTHS]}),
    "sequence_expand": dict(ins={"X": [f32(B, D)], "RefLengths": [LENGTHS]},
                            attrs={"max_len": T}),
    "sequence_softmax": dict(ins={"X": [f32(B, T)], "Lengths": [LENGTHS]}),
    "sequence_reverse": dict(ins={"X": [f32(B, T, D)],
                                  "Lengths": [LENGTHS]}),
    "sequence_slice": dict(ins={"X": [f32(B, T, D)], "Lengths": [LENGTHS],
                                "Offset": [np.array([0, 0], np.int32)],
                                "Length": [np.array([2, 2], np.int32)]}),
    "sequence_concat": dict(ins={"X": [f32(B, T, D)], "XLengths": [LENGTHS],
                                 "Y": [f32(B, T, D)], "YLengths": [LENGTHS]}),
    "context_projection": dict(ins={"X": [f32(B, T, D)],
                                    "Lengths": [LENGTHS]},
                               attrs={"context_start": -1,
                                      "context_length": 3}),
    "sequence_conv": dict(ins={"X": [f32(B, T, D)], "Lengths": [LENGTHS],
                               "Filter": [f32(3 * D, H)]},
                          grad=[("Filter", 0)]),
    # -- recurrent -----------------------------------------------------------
    "lstm": dict(ins={"X": [f32(B, T, D)], "Lengths": [LENGTHS],
                      "W": [f32(D, 4 * H)], "U": [f32(H, 4 * H)],
                      "B": [f32(4 * H)]}, out="Out",
                 grad=[("W", 0), ("U", 0)]),
    "gru": dict(ins={"X": [f32(B, T, D)], "Lengths": [LENGTHS],
                     "W": [f32(D, 3 * H)], "U": [f32(H, 3 * H)],
                     "B": [f32(3 * H)]}, out="Out", grad=[("W", 0)]),
    "simple_rnn": dict(ins={"X": [f32(B, T, D)], "Lengths": [LENGTHS],
                            "W": [f32(D, H)], "U": [f32(H, H)],
                            "B": [f32(H)]}, out="Out",
                       grad=[("W", 0), ("U", 0)]),
    "lstm_unit": dict(ins={"X": [f32(B, 4 * H)], "HPrev": [f32(B, H)],
                           "CPrev": [f32(B, H)], "U": [f32(H, 4 * H)],
                           "B": [f32(4 * H)]}, out="H",
                      grad=[("X", 0), ("U", 0)]),
    "gru_unit": dict(ins={"X": [f32(B, 3 * H)], "HPrev": [f32(B, H)],
                          "U": [f32(H, 3 * H)], "B": [f32(3 * H)]}, out="H",
                     grad=[("X", 0)]),
    "lstm_step": dict(ins={"X": [f32(B, 4 * H)], "CPrev": [f32(B, H)],
                           "WPeep": [f32(3, H)], "B": [f32(4 * H)]},
                      out="H", grad=[("X", 0), ("CPrev", 0), ("WPeep", 0)]),
    "kmax_seq_score": dict(ins={"X": [f32(B, T)], "Lengths": [LENGTHS]},
                           attrs={"beam_size": 2}),
    "sub_nested_seq": dict(
        ins={"X": [f32(B, N, T, D)],
             "SubLengths": [R.randint(1, T + 1, (B, N)).astype(np.int32)],
             "Indices": [R.randint(0, N, (B, 2)).astype(np.int32)]},
        out="Out", grad=[("X", 0)]),
    "cross_entropy_over_beam": dict(
        ins={"X": [f32(B, 4)], "GoldIdx": [np.array([0, 4], np.int32)],
             "GoldScore": [f32(B, 1)]}, grad=[("X", 0)]),
    "equal_scalar": dict(
        ins={"X": [R.randint(0, V, (B, T)).astype(np.int32)]},
        attrs={"value": 3}),
    "dyn_conv2d": dict(
        ins={"X": [f32(B, 5, 5, 2)], "Filter": [f32(B, 3 * 3 * 2 * 4)]},
        attrs={"filter_size": 3, "num_filters": 4, "channels": 2,
               "padding": 1}, grad=[("X", 0), ("Filter", 0)]),
    "scale_sub_region": dict(
        ins={"X": [f32(B, 4, 4, 2)],
             "Indices": [np.tile(np.array([1, 2, 1, 3, 2, 4], np.int32),
                                 (B, 1))]},
        attrs={"value": 2.0}, grad=[("X", 0)]),
    # -- CRF / CTC / NCE -----------------------------------------------------
    "linear_chain_crf": dict(
        ins={"Emission": [f32(B, T, N)],
             "Label": [R.randint(0, N, (B, T)).astype(np.int32)],
             "Lengths": [LENGTHS], "Transition": [f32(N + 2, N)]},
        out="LogLikelihood", grad=[("Emission", 0), ("Transition", 0)]),
    "crf_decoding": dict(
        ins={"Emission": [f32(B, T, N)], "Lengths": [LENGTHS],
             "Transition": [f32(N + 2, N)]}),
    "warpctc": dict(
        ins={"Logits": [jax.nn.log_softmax(jnp.asarray(f32(B, 6, 5)))],
             "LogitsLengths": [np.array([6, 5], np.int32)],
             "Label": [R.randint(1, 5, (B, 2)).astype(np.int32)],
             "LabelLengths": [np.array([2, 1], np.int32)]},
        out="Loss", grad=[("Logits", 0)]),
    "ctc_greedy_decode": dict(
        ins={"Logits": [f32(B, 6, 5)],
             "LogitsLengths": [np.array([6, 5], np.int32)]}),
    "nce": dict(ins={"Input": [f32(B, D)],
                     "Label": [R.randint(0, V, B).astype(np.int32)],
                     "Weight": [f32(V, D)], "Bias": [f32(V)]},
                attrs={"num_neg_samples": 3}, out="Cost"),
    "hierarchical_sigmoid": dict(
        ins={"Input": [f32(B, D)],
             "Label": [R.randint(0, 4, B).astype(np.int32)],
             "InnerW": [f32(8, D)],
             "Paths": [R.randint(0, 8, (4, 3)).astype(np.int32)],
             "Codes": [R.randint(0, 2, (4, 3)).astype(np.int32)]},
        out="Cost", grad=[("Input", 0), ("InnerW", 0)]),
    # -- detection -----------------------------------------------------------
    "prior_box": dict(ins={}, attrs={"feature_hw": (2, 2),
                                     "image_hw": (16, 16),
                                     "min_size": 4.0}),
    "multibox_loss": dict(
        ins={"Loc": [f32(1, 4, 4)],
             "Conf": [f32(1, 4, N)],
             "PriorBox": [R.rand(4, 4).astype(np.float32)],
             "PriorVar": [np.tile(np.float32([0.1, 0.1, 0.2, 0.2]), (4, 1))],
             "GTBox": [R.rand(1, 2, 4).astype(np.float32)],
             "GTLabel": [np.array([[1, 2]], np.int32)],
             "GTMask": [np.array([[1.0, 0.0]], np.float32)]},
        out="Loss"),
    "detection_output": dict(
        ins={"Loc": [f32(1, 4, 4)], "Conf": [f32(1, 4, N)],
             "PriorBox": [R.rand(4, 4).astype(np.float32)],
             "PriorVar": [np.tile(np.float32([0.1, 0.1, 0.2, 0.2]), (4, 1))]},
        attrs={"num_classes": N}),
    # -- optimizer ops -------------------------------------------------------
    "sgd": dict(ins={"Param": [f32(D)], "Grad": [f32(D)],
                     "LearningRate": [np.float32(0.1)]}),
    "momentum": dict(ins={"Param": [f32(D)], "Grad": [f32(D)],
                          "Velocity": [f32(D) * 0],
                          "LearningRate": [np.float32(0.1)]}),
    "adam": dict(ins={"Param": [f32(D)], "Grad": [f32(D)],
                      "Moment1": [f32(D) * 0], "Moment2": [pos32(D)],
                      "Beta1Pow": [np.float32(0.9)],
                      "Beta2Pow": [np.float32(0.999)],
                      "LearningRate": [np.float32(0.1)]}),
    "adagrad": dict(ins={"Param": [f32(D)], "Grad": [f32(D)],
                         "Moment": [pos32(D)],
                         "LearningRate": [np.float32(0.1)]}),
    "adadelta": dict(ins={"Param": [f32(D)], "Grad": [f32(D)],
                          "AvgSquaredGrad": [pos32(D)],
                          "AvgSquaredUpdate": [pos32(D)]}),
    "rmsprop": dict(ins={"Param": [f32(D)], "Grad": [f32(D)],
                         "MeanSquare": [pos32(D)], "Moment": [f32(D) * 0],
                         "LearningRate": [np.float32(0.1)]}),
    "adamax": dict(ins={"Param": [f32(D)], "Grad": [f32(D)],
                        "Moment": [f32(D) * 0], "InfNorm": [pos32(D)],
                        "Beta1Pow": [np.float32(0.9)],
                        "LearningRate": [np.float32(0.1)]}),
    "decayed_adagrad": dict(ins={"Param": [f32(D)], "Grad": [f32(D)],
                                 "Moment": [pos32(D)],
                                 "LearningRate": [np.float32(0.1)]}),
    "proximal_gd": dict(ins={"Param": [f32(D)], "Grad": [f32(D)],
                             "LearningRate": [np.float32(0.1)]},
                        attrs={"l1": 0.01, "l2": 0.01}),
    "proximal_adagrad": dict(ins={"Param": [f32(D)], "Grad": [f32(D)],
                                  "Moment": [pos32(D)],
                                  "LearningRate": [np.float32(0.1)]},
                             attrs={"l1": 0.01, "l2": 0.01}),
    "ftrl": dict(ins={"Param": [f32(D)], "Grad": [f32(D)],
                      "SquaredAccumulator": [pos32(D)],
                      "LinearAccumulator": [f32(D)],
                      "LearningRate": [np.float32(0.1)]},
                 attrs={"l1": 0.01, "l2": 0.01}),
    # -- gen-1 layer-zoo completions ----------------------------------------
    "argmax": dict(ins={"X": [f32(B, V)]}),
    "binary_f1": dict(ins={"X": [f32(B, N)],
                           "Label": [R.randint(0, N, B).astype(np.int32)]}),
    "power": dict(ins={"X": [pos32(B, D)], "W": [np.float32(1.5)]},
                  grad=[("X", 0), ("W", 0)]),
    "slope_intercept": dict(ins={"X": [f32(B, D)]},
                            attrs={"slope": 2.0, "intercept": 0.5},
                            grad=[("X", 0)]),
    "sum_to_one_norm": dict(ins={"X": [pos32(B, D)]}, grad=[("X", 0)]),
    "linear_comb": dict(ins={"X": [f32(B, N * D)], "W": [f32(B, N)]},
                        grad=[("X", 0), ("W", 0)]),
    "repeat": dict(ins={"X": [f32(B, D)]}, attrs={"times": 3}),
    "rotate": dict(ins={"X": [f32(B, T, T, D)]}),
    "seq_reshape": dict(ins={"X": [f32(B, T, 2 * D)]}, attrs={"new_dim": D}),
    "sampling_id": dict(ins={"X": [pos32(B, V)]}, attrs={"seed": 3}),
    "cross_entropy_over_selfnorm": dict(
        ins={"X": [f32(B, V)], "Label": [R.randint(0, V, B).astype(np.int32)]},
        grad=[("X", 0)]),
    "huber_classification": dict(
        ins={"X": [f32(B)],
             "Label": [(R.randint(0, 2, B) * 2 - 1).astype(np.float32)]}),
    "lambda_cost": dict(
        ins={"X": [f32(B, T)],
             "Label": [R.randint(0, 3, (B, T)).astype(np.float32)],
             "Lengths": [LENGTHS]},
        grad=[("X", 0)]),
}

# ops that cannot be run standalone (structural / host-side)
EXEMPT = {"while", "conditional_block", "static_rnn", "beam_search_gen",
          "autodiff_grad", "fill_init"}


def test_every_registered_op_is_covered():
    missing = [op for op in OpRegistry.registered()
               if op not in SPECS and op not in EXEMPT]
    assert not missing, f"registered ops without sweep specs: {missing}"


@pytest.mark.parametrize("op_type", sorted(SPECS))
def test_op_executes_finite(op_type):
    spec = SPECS[op_type]
    compute = OpRegistry.get(op_type)
    ins = {k: [jnp.asarray(v) for v in vs] for k, vs in spec["ins"].items()}
    outs = compute(ins, dict(spec.get("attrs", {})))
    assert isinstance(outs, dict) and outs, f"{op_type} returned {outs!r}"
    for key, vals in outs.items():
        for v in vals:
            arr = np.asarray(v)
            if np.issubdtype(arr.dtype, np.floating):
                assert np.isfinite(arr).all(), f"{op_type}.{key} not finite"


GRAD_CASES = [(op, slot, idx) for op, spec in SPECS.items()
              for slot, idx in spec.get("grad", [])]

# tier-1 velocity (ROADMAP item 5): the costliest numeric-gradient sweeps
# (multi-second central differences over recurrent/DP ops) duplicate
# dedicated ANALYTIC grad tests — lstm/gru: test_rnn_ops + test_pallas
# fused-vs-scan grads; crf/warpctc: test_crf_ctc; nested_lstm:
# test_nested_seq — so they ride the slow lane; the sweep still runs the
# cheap numeric cases and executes EVERY op forward in tier-1.
SLOW_GRAD_CASES = {("warpctc", "Logits", 0),
                   ("linear_chain_crf", "Emission", 0),
                   ("linear_chain_crf", "Transition", 0),
                   ("lstm", "W", 0), ("lstm", "U", 0),
                   ("nested_lstm", "W", 0), ("gru", "W", 0)}


@pytest.mark.parametrize(
    "op_type,slot,idx",
    [pytest.param(*c, marks=pytest.mark.slow)
     if c in SLOW_GRAD_CASES else c for c in GRAD_CASES],
    ids=[f"{o}:{s}{i}" for o, s, i in GRAD_CASES])
def test_op_numeric_gradient(op_type, slot, idx):
    spec = SPECS[op_type]
    compute = OpRegistry.get(op_type)
    attrs = dict(spec.get("attrs", {}))
    out_key = spec.get("out")

    keys = [(k, i) for k, vs in spec["ins"].items() for i in range(len(vs))]
    flat_args = [np.asarray(spec["ins"][k][i]) for k, i in keys]
    wrt = keys.index((slot, idx))

    def f(*args):
        ins = {}
        for (k, i), a in zip(keys, args):
            ins.setdefault(k, []).append(jnp.asarray(a))
        outs = compute(ins, attrs)
        key = out_key or next(iter(outs))
        return jnp.sum(outs[key][0])

    check_grad(f, flat_args, wrt=wrt, rtol=7e-2, atol=5e-3)
