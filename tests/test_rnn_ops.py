"""RNN op tests: masking semantics + gradient checks (analog of
gserver/tests/test_LayerGrad.cpp LSTM/GRU cases and test_RecurrentLayer.cpp)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops import rnn
from op_test import check_grad
import pytest


def _lstm_params(np_rng, D, H):
    w = np_rng.randn(D, 4 * H).astype(np.float32) * 0.3
    u = np_rng.randn(H, 4 * H).astype(np.float32) * 0.3
    b = np_rng.randn(4 * H).astype(np.float32) * 0.1
    return w, u, b


def test_lstm_masking_freezes_state(np_rng):
    D, H = 3, 4
    w, u, b = _lstm_params(np_rng, D, H)
    x = np_rng.randn(2, 6, D).astype(np.float32)
    lengths = jnp.array([6, 3], jnp.int32)
    out, final = rnn.lstm(jnp.asarray(x), lengths, w, u, b)
    # outputs at padded steps are zero
    np.testing.assert_array_equal(np.asarray(out[1, 3:]), 0.0)
    # final state of short seq equals state at its last valid step
    out_full, final_short = rnn.lstm(jnp.asarray(x[1:2, :3]), jnp.array([3]), w, u, b)
    np.testing.assert_allclose(np.asarray(final.h[1]), np.asarray(final_short.h[0]),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(final.c[1]), np.asarray(final_short.c[0]),
                               rtol=1e-5)


# slow: central-difference LSTM grad (26s) — the registry sweep covers lstm W/U
@pytest.mark.slow
def test_lstm_grad(np_rng):
    D, H = 2, 3
    w, u, b = _lstm_params(np_rng, D, H)
    x = np_rng.randn(2, 4, D).astype(np.float32)
    lengths = np.array([4, 2], np.int32)

    def f(xx, ww, uu):
        out, _ = rnn.lstm(jnp.asarray(xx), jnp.asarray(lengths), ww, uu, b)
        return jnp.sum(out * out)

    check_grad(f, [x, w, u], wrt=0)
    check_grad(f, [x, w, u], wrt=1)
    check_grad(f, [x, w, u], wrt=2)


def test_gru_masking(np_rng):
    D, H = 2, 3
    w = np_rng.randn(D, 3 * H).astype(np.float32) * 0.3
    u = np_rng.randn(H, 3 * H).astype(np.float32) * 0.3
    x = np_rng.randn(2, 5, D).astype(np.float32)
    lengths = np.array([5, 2], np.int32)
    out, h = rnn.gru(jnp.asarray(x), jnp.asarray(lengths), w, u)
    np.testing.assert_array_equal(np.asarray(out[1, 2:]), 0.0)


# slow: central-difference GRU grad (31s) — the lstm_grad precedent;
# analytic masked-grad parity (scan vs fused, lengths in-loop) stays
# tier-1 in test_pallas.py::test_gru_fused_backward_kernel_matches_scan_grads
@pytest.mark.slow
def test_gru_grad(np_rng):
    D, H = 2, 3
    w = np_rng.randn(D, 3 * H).astype(np.float32) * 0.3
    u = np_rng.randn(H, 3 * H).astype(np.float32) * 0.3
    x = np_rng.randn(2, 5, D).astype(np.float32)
    lengths = np.array([5, 2], np.int32)

    def f(xx, ww):
        o, _ = rnn.gru(jnp.asarray(xx), jnp.asarray(lengths), ww, u)
        return jnp.sum(jnp.square(o))

    check_grad(f, [x, w], wrt=0)
    check_grad(f, [x, w], wrt=1)


def test_bidirectional_concat(np_rng):
    D, H = 3, 4
    w, u, b = _lstm_params(np_rng, D, H)
    w2, u2, b2 = _lstm_params(np_rng, D, H)
    x = jnp.asarray(np_rng.randn(2, 5, D).astype(np.float32))
    lengths = jnp.array([5, 3], jnp.int32)
    out = rnn.bidirectional(rnn.lstm, x, lengths,
                            dict(w=w, u=u, b=b), dict(w=w2, u=u2, b=b2))
    assert out.shape == (2, 5, 2 * H)
    # reverse direction of short seq must equal running the truncated seq reversed
    out_b, _ = rnn.lstm(x[1:2, :3], jnp.array([3]), w2, u2, b2, reverse=True)
    np.testing.assert_allclose(np.asarray(out[1, :3, H:]), np.asarray(out_b[0]),
                               rtol=1e-5, atol=1e-6)


def test_fused_lstm_vmem_guard_falls_back():
    """Shapes with no VMEM-legal (batch-tile, time-chunk) plan must fall
    back to the scan instead of failing to compile; shapes with one must
    prefer MXU-feeding wide batch tiles (the widened-coverage contract)."""
    from paddle_tpu.ops import rnn as R
    # the textcls bench family (h256, len<=100, B=64+) now plans a WIDE
    # batch tile — the whole point of the time-chunked widening: 8-row
    # tiles starved the MXU and lost the B=64 crossover
    blk, chunk = R._fused_plan(100, 256, seq_h_units=6, batch=64)
    assert blk >= 32 and blk % 8 == 0 and 8 <= chunk <= 100
    # long sequences fit by shrinking the chunk, not by falling back
    blk, chunk = R._fused_plan(1024, 512, seq_h_units=6, batch=64)
    assert blk % 8 == 0 and chunk < 1024
    # sub-8 batches run a single exact-width tile
    assert R._fused_plan(100, 256, batch=5)[0] == 5
    # h1280: u alone is 26 MB -> no plan, scan
    assert R._fused_plan(100, 1280, batch=64) is None
    # backward: h256 chunks T=100 into wide-tile launches; h1280 replays
    plan = R._fused_bwd_plan(100, 256, 4, 11, 64)
    assert plan is not None and plan[0] >= 32 and 8 <= plan[1] <= 100
    assert R._fused_bwd_plan(100, 1280, 4, 11, 64) is None
    # fused=True on a too-big shape silently uses the scan
    rs = np.random.RandomState(0)
    B, T, D, H = 2, 40, 3, 4
    x = jnp.asarray(rs.randn(B, T, D), jnp.float32)
    lens = jnp.asarray([40, 20], jnp.int32)
    w = jnp.asarray(rs.randn(D, 4 * H) * 0.3, jnp.float32)
    u = jnp.asarray(rs.randn(H, 4 * H) * 0.3, jnp.float32)
    ref, _ = R.lstm(x, lens, w, u)
    got, _ = R.lstm(x, lens, w, u, fused=True)      # CPU -> scan fallback
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6)
