"""Native host-runtime tests — in-process, like the reference's Go
table-driven master/pserver tests (SURVEY.md §4.3: go/master/service_test.go)."""

import os
import threading

import pytest

from paddle_tpu.runtime import (HostArena, RecordReader, RecordWriter,
                                TaskMaster, native_available)

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native toolchain unavailable")


def test_master_dispatch_cycle():
    m = TaskMaster(timeout_s=60, failure_max=3)
    m.set_dataset([f"chunk-{i}" for i in range(4)])
    seen = []
    while True:
        t = m.get_task(now=0.0)
        if t is None:
            break
        seen.append(t[1])
        m.task_finished(t[0])
    assert sorted(seen) == [f"chunk-{i}" for i in range(4)]
    assert m.pass_finished()
    # explicit next pass refills todo and bumps epoch (ErrPassAfter analog)
    assert m.new_pass()
    todo, pending, done, disc, epoch = m.stats()
    assert todo == 4 and pending == 0 and epoch == 1


def test_master_timeout_requeue_and_discard():
    m = TaskMaster(timeout_s=10, failure_max=2)
    m.set_dataset(["a"])
    tid, payload = m.get_task(now=0.0)
    assert payload == "a"
    # not yet due
    assert m.tick(now=5.0) == 0
    # overdue -> requeued (failure 1)
    assert m.tick(now=11.0) == 1
    tid2, _ = m.get_task(now=12.0)
    # second timeout hits failure_max -> discarded
    assert m.tick(now=30.0) == 1
    todo, pending, done, disc, epoch = m.stats()
    assert disc == 1 and todo == 0 and pending == 0


def test_master_explicit_failure():
    m = TaskMaster(timeout_s=60, failure_max=3)
    m.set_dataset(["x"])
    tid, _ = m.get_task(now=0.0)
    assert m.task_failed(tid) is False      # requeued
    tid, _ = m.get_task(now=1.0)
    assert m.task_failed(tid) is False
    tid, _ = m.get_task(now=2.0)
    assert m.task_failed(tid) is True       # discarded at failure_max


def test_master_snapshot_restore(tmp_path):
    m = TaskMaster(timeout_s=60, failure_max=3)
    m.set_dataset([f"c{i}" for i in range(6)])
    t1 = m.get_task(now=0.0)
    t2 = m.get_task(now=0.0)
    m.task_finished(t1[0])
    snap = str(tmp_path / "master.snap")
    m.snapshot(snap)

    m2 = TaskMaster(timeout_s=60, failure_max=3)
    m2.restore(snap)
    todo, pending, done, disc, epoch = m2.stats()
    # pending task re-queued as todo on recovery; the finished one preserved
    assert pending == 0 and done == 1 and todo == 5
    payloads = []
    while True:
        t = m2.get_task(now=0.0)
        if t is None:
            break
        payloads.append(t[1])
        m2.task_finished(t[0])
    assert t2[1] in payloads


def test_master_threaded_consumers():
    m = TaskMaster(timeout_s=60, failure_max=3)
    m.set_dataset([f"c{i}" for i in range(64)])
    got = []
    lock = threading.Lock()

    def worker():
        while True:
            t = m.get_task(now=0.0)
            if t is None:
                return
            with lock:
                got.append(t[1])
            m.task_finished(t[0])

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(got) == sorted(f"c{i}" for i in range(64))


def test_recordio_roundtrip_and_corruption(tmp_path):
    path = str(tmp_path / "data.ptr")
    payloads = [b"hello", b"", b"x" * 10000, bytes(range(256))]
    with RecordWriter(path) as w:
        for p in payloads:
            w.write(p)
    with RecordReader(path) as r:
        assert list(r) == payloads
    # flip one payload byte -> CRC error
    raw = bytearray(open(path, "rb").read())
    raw[4 + 8 + 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    with RecordReader(path) as r:
        with pytest.raises(IOError):
            list(r)


def test_arena_alloc_free_coalesce():
    a = HostArena(total=1 << 12, min_block=256)
    o1 = a.alloc(256)
    o2 = a.alloc(256)
    o3 = a.alloc(1024)
    assert len({o1, o2, o3}) == 3
    total, in_use, largest = a.stats()
    assert in_use == 256 + 256 + 1024
    a.free(o1)
    a.free(o2)
    a.free(o3)
    total, in_use, largest = a.stats()
    assert in_use == 0 and largest == total   # fully coalesced
    # whole-arena alloc works after coalesce
    big = a.alloc(1 << 12)
    with pytest.raises(MemoryError):
        a.alloc(256)
    a.free(big)
    with pytest.raises(ValueError):
        a.free(12345)


def test_arena_rejects_non_pow2():
    with pytest.raises(ValueError):
        HostArena(total=3000, min_block=256)


def test_host_optimizer_matches_numpy_adam():
    import numpy as np
    from paddle_tpu.runtime import HostOptimizer
    rs = np.random.RandomState(0)
    p0 = rs.randn(32).astype(np.float32)
    opt = HostOptimizer("adam", p0, lr=0.01)
    # numpy reference
    p, m, v = p0.astype(np.float64).copy(), np.zeros(32), np.zeros(32)
    for t in range(1, 6):
        g = rs.randn(32).astype(np.float32)
        opt.update(g)
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        mh = m / (1 - 0.9 ** t)
        vh = v / (1 - 0.999 ** t)
        p -= 0.01 * mh / (np.sqrt(vh) + 1e-6)
    np.testing.assert_allclose(opt.param, p, rtol=1e-4, atol=1e-5)


def test_host_optimizer_serialize_roundtrip():
    import numpy as np
    from paddle_tpu.runtime import HostOptimizer
    rs = np.random.RandomState(1)
    p0 = rs.randn(16).astype(np.float32)
    a = HostOptimizer("adagrad", p0, lr=0.1)
    for _ in range(3):
        a.update(rs.randn(16).astype(np.float32))
    blob = a.serialize()
    b = HostOptimizer("adagrad", p0, lr=0.1)
    b.deserialize(blob)
    g = rs.randn(16).astype(np.float32)
    a.update(g)
    b.update(g)
    np.testing.assert_allclose(a.param, b.param, rtol=1e-6)


def test_host_optimizer_sparse_rows():
    import numpy as np
    from paddle_tpu.runtime import HostOptimizer
    table = np.zeros((8, 4), np.float32)
    opt = HostOptimizer("sgd", table, lr=1.0)
    rows = np.array([1, 5], np.int32)
    grad = np.ones((2, 4), np.float32)
    opt.update_rows(rows, grad)
    out = opt.param
    assert out[1].sum() == -4 and out[5].sum() == -4 and out[0].sum() == 0


def test_master_large_payload_not_truncated():
    """Payloads >= the client's initial 4096-byte buffer must round-trip: the
    C side returns -3 + required length without consuming, client retries
    (recordio peek pattern; ADVICE r1 medium)."""
    m = TaskMaster(timeout_s=60, failure_max=3)
    big = "p" * 20000
    m.set_dataset([big])
    tid, payload = m.get_task(now=0.0)
    assert payload == big
    m.task_finished(tid)
    assert m.pass_finished()
