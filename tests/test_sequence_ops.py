"""Sequence-op semantics tests (analog of gserver sequence layer tests in
test_LayerGrad.cpp and test_SeqSliceLayerGrad.cpp)."""

import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops import sequence as seq
from op_test import check_grad


def _batch(np_rng, B=2, T=5, D=3):
    x = np_rng.randn(B, T, D).astype(np.float32)
    lengths = np.array([5, 3], np.int32)
    return jnp.asarray(x), jnp.asarray(lengths), x


def test_sequence_pool_types(np_rng):
    x, lengths, xn = _batch(np_rng)
    avg = seq.sequence_pool(x, lengths, "average")
    np.testing.assert_allclose(np.asarray(avg[1]), xn[1, :3].mean(0), rtol=1e-5)
    mx = seq.sequence_pool(x, lengths, "max")
    np.testing.assert_allclose(np.asarray(mx[1]), xn[1, :3].max(0), rtol=1e-5)
    last = seq.sequence_pool(x, lengths, "last")
    np.testing.assert_allclose(np.asarray(last[1]), xn[1, 2], rtol=1e-5)
    first = seq.sequence_pool(x, lengths, "first")
    np.testing.assert_allclose(np.asarray(first[1]), xn[1, 0], rtol=1e-5)
    sqrt = seq.sequence_pool(x, lengths, "sqrt")
    np.testing.assert_allclose(np.asarray(sqrt[1]), xn[1, :3].sum(0) / np.sqrt(3),
                               rtol=1e-5)


def test_sequence_pool_grads(np_rng):
    x, lengths, xn = _batch(np_rng)

    for ptype in ("average", "sum", "max", "sqrt"):
        def f(xx):
            return jnp.sum(jnp.square(seq.sequence_pool(jnp.asarray(xx), lengths, ptype)))
        check_grad(f, [xn], wrt=0)


def test_sequence_reverse(np_rng):
    x, lengths, xn = _batch(np_rng)
    r = seq.sequence_reverse(x, lengths)
    np.testing.assert_allclose(np.asarray(r[1, :3]), xn[1, :3][::-1], rtol=1e-6)
    # padding untouched positions remain from identity mapping
    np.testing.assert_allclose(np.asarray(r[0]), xn[0][::-1], rtol=1e-6)


def test_sequence_expand():
    v = jnp.asarray(np.arange(6, dtype=np.float32).reshape(2, 3))
    lengths = jnp.asarray(np.array([2, 4], np.int32))
    out = seq.sequence_expand(v, lengths, max_len=5)
    assert out.shape == (2, 5, 3)
    np.testing.assert_array_equal(np.asarray(out[0, 0]), np.asarray(v[0]))
    np.testing.assert_array_equal(np.asarray(out[0, 2:]), 0.0)
    np.testing.assert_array_equal(np.asarray(out[1, 3]), np.asarray(v[1]))


def test_sequence_slice(np_rng):
    x, lengths, xn = _batch(np_rng)
    out = seq.sequence_slice(x, lengths, jnp.array([1, 0]), jnp.array([2, 3]), max_out=4)
    np.testing.assert_allclose(np.asarray(out[0, :2]), xn[0, 1:3], rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(out[0, 2:]), 0.0)
    np.testing.assert_allclose(np.asarray(out[1, :3]), xn[1, :3], rtol=1e-6)


def test_sequence_concat(np_rng):
    a = jnp.asarray(np_rng.randn(2, 3, 2).astype(np.float32))
    b = jnp.asarray(np_rng.randn(2, 3, 2).astype(np.float32))
    la = jnp.array([2, 3])
    lb = jnp.array([3, 1])
    out, lengths = seq.sequence_concat(a, la, b, lb)
    np.testing.assert_array_equal(np.asarray(lengths), [5, 4])
    np.testing.assert_allclose(np.asarray(out[0, :2]), np.asarray(a[0, :2]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out[0, 2:5]), np.asarray(b[0, :3]), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(out[0, 5:]), 0.0)


def test_context_projection_identity_window(np_rng):
    x, lengths, xn = _batch(np_rng)
    out = seq.context_projection(x, lengths, 0, 1)
    np.testing.assert_allclose(np.asarray(out), xn * np.asarray(
        (np.arange(5)[None, :] < np.asarray(lengths)[:, None])[..., None]), rtol=1e-6)


def test_context_projection_negative_offset_no_padding_leak(np_rng):
    x, lengths, xn = _batch(np_rng)  # lengths [5, 3]
    out = seq.context_projection(x, lengths, -1, 1)
    # destination padding timesteps must be zero even for negative offsets
    np.testing.assert_array_equal(np.asarray(out[1, 3:]), 0.0)
    # valid region: position t holds x[t-1]
    np.testing.assert_allclose(np.asarray(out[1, 1:3]), xn[1, 0:2], rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(out[1, 0]), 0.0)


def test_sequence_conv_shapes_and_grad(np_rng):
    x, lengths, xn = _batch(np_rng, D=3)
    filt = np_rng.randn(9, 4).astype(np.float32) * 0.3

    out = seq.sequence_conv(x, lengths, jnp.asarray(filt))
    assert out.shape == (2, 5, 4)
    # padded outputs masked
    np.testing.assert_array_equal(np.asarray(out[1, 3:]), 0.0)

    def f(xx, ff):
        return jnp.sum(jnp.square(seq.sequence_conv(jnp.asarray(xx), lengths, ff)))
    check_grad(f, [xn, filt], wrt=0)
    check_grad(f, [xn, filt], wrt=1)
