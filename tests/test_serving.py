"""Continuous batching (paddle_tpu/serving.py): requests with MIXED prompt
and generation lengths share a fixed slot pool; each request's greedy
continuation must be token-for-token identical to decoding it ALONE through
generate_cached — in-flight batching must not change anyone's tokens."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models import TransformerLM
from paddle_tpu.serving import ContinuousBatcher, Request

VOCAB, D, H, L, MAX_LEN = 97, 32, 4, 2, 128


@pytest.fixture(scope="module")
def model_and_params():
    model = TransformerLM(VOCAB, d_model=D, n_heads=H, n_layers=L,
                          max_len=MAX_LEN)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _solo(model, params, prompt, steps):
    out = model.generate_cached(params, jnp.asarray(prompt[None]),
                                steps=steps)
    return np.asarray(out)[0, len(prompt):]


def test_continuous_batching_matches_solo_decode(model_and_params):
    model, params = model_and_params
    rs = np.random.RandomState(3)
    reqs = []
    for rid in range(11):        # more requests than slots -> real churn
        plen = int(rs.randint(3, 40))
        gen = int(rs.randint(1, 37))
        reqs.append(Request(rid, rs.randint(0, VOCAB, plen), gen))
    b = ContinuousBatcher(model, params, slots=4, segment=8,
                          cache_bucket=32)
    got = b.serve(reqs)
    assert sorted(got) == [r.rid for r in reqs]
    for r in reqs:
        want = _solo(model, params, r.prompt, r.max_new)
        np.testing.assert_array_equal(
            got[r.rid], want,
            err_msg=f"request {r.rid} (prompt {len(r.prompt)}, "
                    f"gen {r.max_new}) diverged under continuous batching")


def test_schedule_fifo_and_longest_first_agree(model_and_params):
    """Admission order is a throughput knob only: per-request outputs are
    identical under both schedules."""
    model, params = model_and_params
    rs = np.random.RandomState(9)
    reqs = [Request(i, rs.randint(0, VOCAB, int(rs.randint(3, 30))),
                    int(rs.randint(1, 25))) for i in range(7)]
    out = {}
    for sched in ("fifo", "longest_first"):
        b = ContinuousBatcher(model, params, slots=3, segment=8,
                              cache_bucket=32, schedule=sched)
        out[sched] = b.serve([Request(r.rid, r.prompt, r.max_new)
                              for r in reqs])
    assert sorted(out["fifo"]) == sorted(out["longest_first"])
    for rid in out["fifo"]:
        np.testing.assert_array_equal(out["fifo"][rid],
                                      out["longest_first"][rid])


def test_continuous_batching_eos_truncates(model_and_params):
    model, params = model_and_params
    rs = np.random.RandomState(5)
    prompt = rs.randint(0, VOCAB, 9)
    full = _solo(model, params, prompt, 24)
    eos = int(full[7])           # force an EOS hit mid-stream
    b = ContinuousBatcher(model, params, slots=2, segment=8,
                          cache_bucket=32)
    got = b.serve([Request(0, prompt, 24, eos_id=eos)])
    first_hit = int(np.nonzero(full == eos)[0][0])
    np.testing.assert_array_equal(got[0], full[:first_hit])


def test_continuous_batching_budget_caps_at_max_len(model_and_params):
    model, params = model_and_params
    rs = np.random.RandomState(7)
    prompt = rs.randint(0, VOCAB, MAX_LEN - 5)
    b = ContinuousBatcher(model, params, slots=2, segment=8,
                          cache_bucket=32)
    got = b.serve([Request(0, prompt, 1000)])   # budget > room
    assert len(got[0]) == 5                     # clamped to max_len - prompt
    want = _solo(model, params, prompt, 5)
    np.testing.assert_array_equal(got[0], want)


def test_idle_slot_parking_near_max_len(model_and_params):
    """With an empty queue, drained slots keep idle-decoding; their garbage
    positions must be parked before reaching max_len (the park_idle path) so
    a long-running request's neighbors never clamp-write, and the long
    request itself stays exact."""
    model, params = model_and_params
    rs = np.random.RandomState(11)
    prompt = rs.randint(0, VOCAB, 5)
    gen = MAX_LEN - 5 - 1                 # as long a run as max_len allows
    b = ContinuousBatcher(model, params, slots=3, segment=8,
                          cache_bucket=32)
    # one real request; the two other slots idle for ~gen/8 segments, far
    # past the parking threshold of max_len - 2*segment
    got = b.serve([Request(0, prompt, gen)])
    want = _solo(model, params, prompt, gen)
    np.testing.assert_array_equal(got[0], want)


def test_zero_length_prompt_rejected(model_and_params):
    """pos==0 ragged-prefill gather would wrap to the last padded position
    and emit a garbage first token — an empty prompt must be rejected in
    validation, same as an over-long one (ADVICE r5)."""
    model, params = model_and_params
    b = ContinuousBatcher(model, params, slots=2, segment=8,
                          cache_bucket=32)
    with pytest.raises(ValueError, match="empty prompt"):
        b.serve([Request(0, np.zeros((0,), np.int32), 4)])
    # a mixed batch is rejected before any slot state is touched
    with pytest.raises(ValueError, match="request 1"):
        b.serve([Request(0, np.array([3, 5], np.int32), 4),
                 Request(1, np.array([], np.int32), 4)])
