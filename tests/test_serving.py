"""Continuous batching (paddle_tpu/serving/batcher.py): requests with MIXED prompt
and generation lengths share a fixed slot pool; each request's greedy
continuation must be token-for-token identical to decoding it ALONE through
generate_cached — in-flight batching must not change anyone's tokens."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import obs
from paddle_tpu.models import TransformerLM
from paddle_tpu.serving import ContinuousBatcher, Request, SpeculativeDecoder

VOCAB, D, H, L, MAX_LEN = 97, 32, 4, 2, 128


@pytest.fixture(scope="module")
def model_and_params():
    model = TransformerLM(VOCAB, d_model=D, n_heads=H, n_layers=L,
                          max_len=MAX_LEN)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _solo(model, params, prompt, steps, _bucket=12):
    """Reference: the request decoded ALONE through generate_cached.

    Tier-1 velocity (ROADMAP item 5, shared traced executables): `steps`
    is padded to a bucket multiple and the stream truncated — greedy
    continuation is prefix-stable, so tokens are identical while the
    dozen distinct per-request scan lengths collapse onto ~3 compiled
    scan programs (each request still pays its own ragged prefill)."""
    padded = min(-(-steps // _bucket) * _bucket,
                 model.max_len - len(prompt))
    out = model.generate_cached(params, jnp.asarray(prompt[None]),
                                steps=padded)
    return np.asarray(out)[0, len(prompt):len(prompt) + steps]


# slow: 55s of solo re-decodes; pinned==solo holds transitively tier-1
# via test_serving_paged.py (paged==solo AND paged==pinned on the same
# mixed workload), and fifo/longest_first parity below keeps the pinned
# batcher exercised
@pytest.mark.slow
def test_continuous_batching_matches_solo_decode(model_and_params):
    model, params = model_and_params
    rs = np.random.RandomState(3)
    reqs = []
    for rid in range(11):        # more requests than slots -> real churn
        plen = int(rs.randint(3, 40))
        gen = int(rs.randint(1, 37))
        reqs.append(Request(rid, rs.randint(0, VOCAB, plen), gen))
    b = ContinuousBatcher(model, params, slots=4, segment=8,
                          cache_bucket=32)
    got = b.serve(reqs)
    assert sorted(got) == [r.rid for r in reqs]
    for r in reqs:
        want = _solo(model, params, r.prompt, r.max_new)
        np.testing.assert_array_equal(
            got[r.rid], want,
            err_msg=f"request {r.rid} (prompt {len(r.prompt)}, "
                    f"gen {r.max_new}) diverged under continuous batching")


def test_schedule_fifo_and_longest_first_agree(model_and_params):
    """Admission order is a throughput knob only: per-request outputs are
    identical under both schedules."""
    model, params = model_and_params
    rs = np.random.RandomState(9)
    reqs = [Request(i, rs.randint(0, VOCAB, int(rs.randint(3, 30))),
                    int(rs.randint(1, 25))) for i in range(7)]
    out = {}
    for sched in ("fifo", "longest_first"):
        b = ContinuousBatcher(model, params, slots=3, segment=8,
                              cache_bucket=32, schedule=sched)
        out[sched] = b.serve([Request(r.rid, r.prompt, r.max_new)
                              for r in reqs])
    assert sorted(out["fifo"]) == sorted(out["longest_first"])
    for rid in out["fifo"]:
        np.testing.assert_array_equal(out["fifo"][rid],
                                      out["longest_first"][rid])


def test_continuous_batching_eos_truncates(model_and_params):
    model, params = model_and_params
    rs = np.random.RandomState(5)
    prompt = rs.randint(0, VOCAB, 9)
    full = _solo(model, params, prompt, 24)
    eos = int(full[7])           # force an EOS hit mid-stream
    b = ContinuousBatcher(model, params, slots=2, segment=8,
                          cache_bucket=32)
    got = b.serve([Request(0, prompt, 24, eos_id=eos)])
    first_hit = int(np.nonzero(full == eos)[0][0])
    np.testing.assert_array_equal(got[0], full[:first_hit])


def test_continuous_batching_budget_caps_at_max_len(model_and_params):
    model, params = model_and_params
    rs = np.random.RandomState(7)
    prompt = rs.randint(0, VOCAB, MAX_LEN - 5)
    b = ContinuousBatcher(model, params, slots=2, segment=8,
                          cache_bucket=32)
    got = b.serve([Request(0, prompt, 1000)])   # budget > room
    assert len(got[0]) == 5                     # clamped to max_len - prompt
    want = _solo(model, params, prompt, 5)
    np.testing.assert_array_equal(got[0], want)


def test_idle_slot_parking_near_max_len(model_and_params):
    """With an empty queue, drained slots keep idle-decoding; their garbage
    positions must be parked before reaching max_len (the park_idle path) so
    a long-running request's neighbors never clamp-write, and the long
    request itself stays exact."""
    model, params = model_and_params
    rs = np.random.RandomState(11)
    prompt = rs.randint(0, VOCAB, 5)
    gen = MAX_LEN - 5 - 1                 # as long a run as max_len allows
    b = ContinuousBatcher(model, params, slots=3, segment=8,
                          cache_bucket=32)
    # one real request; the two other slots idle for ~gen/8 segments, far
    # past the parking threshold of max_len - 2*segment
    got = b.serve([Request(0, prompt, gen)])
    want = _solo(model, params, prompt, gen)
    np.testing.assert_array_equal(got[0], want)


@pytest.mark.slow
def test_continuous_batching_int8_kv_matches_solo_int8(model_and_params):
    """The quantized-KV exactness contract for serving: an int8-cache
    batcher's tokens equal SOLO decode at the same kv_dtype (the
    quantization error is the configuration's, batching adds none).

    slow: ~14s, and the int8 serving machinery it proves (quantize on
    append, in-read dequant, padded-scale convention) is tier-1-covered
    by test_serving_paged.py::test_paged_int8_matches_solo_int8 +
    tests/test_serving_prefix.py int8 hits on the SAME prefill/
    decode_step cells; the pinned pool's int8 storage layout has no
    other code of its own (PR 12 --durations=25 triage)."""
    model, params = model_and_params
    rs = np.random.RandomState(13)
    reqs = [Request(rid, rs.randint(0, VOCAB, int(rs.randint(3, 30))),
                    int(rs.randint(1, 25))) for rid in range(3)]
    b = ContinuousBatcher(model, params, slots=2, segment=8,
                          cache_bucket=32, kv_dtype="int8")
    got = b.serve(reqs)
    for r in reqs:
        want = np.asarray(model.generate_fused(
            params, jnp.asarray(r.prompt[None]), steps=r.max_new,
            kv_dtype="int8"))[0, len(r.prompt):]
        np.testing.assert_array_equal(got[r.rid], want,
                                      err_msg=f"request {r.rid}")


def test_continuous_batching_counts_segment_dispatches(model_and_params):
    """One dispatch per SEGMENT (not per token, not per op) is the
    batcher's economics — decode.dispatches_total proves it and
    tokens_total matches the delivered stream."""
    model, params = model_and_params
    prompt = np.random.RandomState(3).randint(0, VOCAB, 9)
    r = obs.MetricsRegistry()
    with obs.ObsSession(registry=r).installed():
        got = ContinuousBatcher(model, params, slots=2, segment=8,
                                cache_bucket=32).serve(
            [Request(0, prompt, 20)])
    samples = r.collect()
    segs = [s["value"] for s in samples
            if s["name"] == "decode.dispatches_total"
            and s["labels"].get("route") == "serve_segment"]
    assert segs and segs[0] == -(-20 // 8)        # ceil(tokens/segment)
    toks = [s["value"] for s in samples
            if s["name"] == "decode.tokens_total"]
    assert sum(toks) == len(got[0]) == 20


# -- speculative decoding ---------------------------------------------------


class _ScriptedDraft:
    """A draft exposing the model interface but proposing SCRIPTED tokens —
    the 'any acceptance pattern' adversary: constant garbage (never
    accepted), or an oracle replay (always accepted)."""

    def __init__(self, script, max_len):
        self.script = script          # [B] -> proposal, called per step
        self.max_len = max_len

    def prefill(self, params, prompt):
        return {"pos": jnp.zeros((prompt.shape[0],), jnp.int32)}, \
            jnp.zeros((prompt.shape[0], VOCAB), jnp.float32)

    def decode_step(self, params, cell, tokens):
        tok = self.script(tokens)
        onehot = jax.nn.one_hot(tok, VOCAB, dtype=jnp.float32)
        return onehot, cell


def _greedy(model, params, prompt, steps):
    return np.asarray(model.generate_cached(
        params, jnp.asarray(prompt), steps=steps))[:, prompt.shape[1]:]


@pytest.mark.parametrize("k", [1, 3, 6])
def test_speculative_self_draft_exact_and_fully_accepted(model_and_params,
                                                         k):
    """draft == target: every proposal accepted, output still exactly
    greedy — and the acceptance stats say so."""
    model, params = model_and_params
    prompt = np.random.RandomState(17).randint(0, VOCAB, (2, 6))
    want = _greedy(model, params, prompt, 18)
    sd = SpeculativeDecoder(model, params, model, params, k=k)
    got, stats = sd.generate(prompt, 18)
    np.testing.assert_array_equal(got, want)
    assert stats["acceptance_rate"] == 1.0
    if k > 1:
        assert stats["rounds"] < 18          # fewer target passes


def test_speculative_adversarial_draft_still_exact(model_and_params):
    """A draft that NEVER matches the target (constant garbage proposals):
    zero acceptance, one token per round, output still exactly greedy —
    the for-any-acceptance-pattern clause."""
    model, params = model_and_params
    prompt = np.random.RandomState(19).randint(0, VOCAB, (2, 5))
    want = _greedy(model, params, prompt, 10)
    # constant proposals can only collide with greedy by accident on 2
    # fixed rows; pick a token neither row ever emits
    bad = int((want.max() + 1) % VOCAB)
    assert not (want == bad).any()
    draft = _ScriptedDraft(lambda toks: jnp.full_like(toks, bad), MAX_LEN)
    sd = SpeculativeDecoder(model, params, draft, None, k=4)
    got, stats = sd.generate(prompt, 10)
    np.testing.assert_array_equal(got, want)
    assert stats["accepted"] == 0
    # prefill emits token 1; each zero-acceptance round emits exactly one
    assert stats["rounds"] == 9


def test_speculative_mixed_draft_and_int8_self_draft(model_and_params):
    """A weaker real draft (random tiny model) and the bench's int8
    self-speculation draft: partial acceptance, exact output either way."""
    model, params = model_and_params
    prompt = np.random.RandomState(23).randint(0, VOCAB, (3, 7))
    want = _greedy(model, params, prompt, 15)
    tiny = TransformerLM(VOCAB, d_model=16, n_heads=2, n_layers=1,
                         max_len=MAX_LEN)
    tparams = tiny.init(jax.random.PRNGKey(9))
    for draft, dparams, dkv in ((tiny, tparams, None),
                                (model, params, "int8")):
        sd = SpeculativeDecoder(model, params, draft, dparams,
                                k=4, draft_kv_dtype=dkv)
        got, stats = sd.generate(prompt, 15)
        np.testing.assert_array_equal(got, want)
        assert 0.0 <= stats["acceptance_rate"] <= 1.0


def test_speculative_budget_validation(model_and_params):
    model, params = model_and_params
    sd = SpeculativeDecoder(model, params, model, params, k=4)
    long_prompt = np.zeros((1, MAX_LEN - 6), np.int32)
    with pytest.raises(ValueError, match="max_len"):
        sd.generate(long_prompt, 10)
    with pytest.raises(ValueError, match="empty prompt"):
        sd.generate(np.zeros((1, 0), np.int32), 4)


def test_zero_length_prompt_rejected(model_and_params):
    """pos==0 ragged-prefill gather would wrap to the last padded position
    and emit a garbage first token — an empty prompt must be rejected in
    validation, same as an over-long one (ADVICE r5)."""
    model, params = model_and_params
    b = ContinuousBatcher(model, params, slots=2, segment=8,
                          cache_bucket=32)
    with pytest.raises(ValueError, match="empty prompt"):
        b.serve([Request(0, np.zeros((0,), np.int32), 4)])
    # a mixed batch is rejected before any slot state is touched
    with pytest.raises(ValueError, match="request 1"):
        b.serve([Request(0, np.array([3, 5], np.int32), 4),
                 Request(1, np.array([], np.int32), 4)])
