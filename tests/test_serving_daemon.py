"""The serving daemon over the native RPC plane (serving/daemon.py):
srv_submit/srv_poll/srv_cancel ride the ptms_set_fallback unknown-op path,
backpressure is a STRUCTURED reply (never a dead connection), cancel frees
pages, and the engine's TTFT/TPOT histograms surface through the
master-side cluster aggregator (obs_stats) — the ROADMAP item 2
acceptance surface, end to end."""

import os
import re
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.runtime import native_available
from paddle_tpu.runtime.master_service import MasterClient, MasterServer

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native host runtime unavailable")

VOCAB, D, H, L, MAX_LEN = 97, 32, 4, 2, 128


@pytest.fixture(scope="module")
def model_and_params():
    from paddle_tpu.models import TransformerLM
    model = TransformerLM(VOCAB, d_model=D, n_heads=H, n_layers=L,
                          max_len=MAX_LEN)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


@pytest.fixture()
def daemon(model_and_params):
    from paddle_tpu import obs
    from paddle_tpu.serving import ServingDaemon, ServingEngine
    model, params = model_and_params
    reg = obs.MetricsRegistry()
    session = obs.ObsSession(registry=reg).install()
    eng = ServingEngine(model, params, slots=2, segment=8, page_block=8,
                        cache_bucket=32, queue_cap=3)
    d = ServingDaemon(eng, obs_interval_s=0.1).start()
    try:
        yield d, reg
    finally:
        d.stop()
        session.uninstall()


def _drain(client, rid, timeout=60.0):
    deadline = time.monotonic() + timeout
    cursor, toks = 0, []
    while True:
        got, done, reason = client.poll(rid, cursor)
        toks.extend(got)
        cursor += len(got)
        if done:
            return np.asarray(toks, np.int32), reason
        assert time.monotonic() < deadline, "poll drain timed out"
        time.sleep(0.02)


def test_daemon_e2e_exact_streaming_and_slo_metrics(daemon,
                                                    model_and_params):
    """Submit/poll over the wire: greedy tokens exactly equal solo decode,
    stats serve, and the TTFT/TPOT histograms appear in the aggregated
    obs_stats view (worker label 'serving')."""
    from paddle_tpu.serving import ServingClient
    model, params = model_and_params
    d, reg = daemon
    c = ServingClient(*d.address)
    rs = np.random.RandomState(5)
    prompt = rs.randint(0, VOCAB, 9)
    out = c.generate(prompt, 20)
    want = np.asarray(model.generate_cached(
        params, jnp.asarray(prompt[None]), steps=20))[0, 9:]
    np.testing.assert_array_equal(out, want)

    st = c.serving_stats()
    assert st["pages_total"] > 0 and st["queue_depth"] == 0
    assert st["rpc_conns"] >= 1          # we are connected right now

    # the daemon pushes the engine registry into the master-side
    # aggregator; obs_stats then serves the SLO pair fleet-style
    deadline = time.monotonic() + 10.0
    names = set()
    mc = MasterClient(*d.address)
    while time.monotonic() < deadline:
        workers, samples = mc.obs_stats()
        names = {s["name"] for s in samples}
        if "serving.ttft_seconds" in names and \
                "serving.tpot_seconds" in names:
            break
        time.sleep(0.1)
    assert "serving.ttft_seconds" in names, names
    assert "serving.tpot_seconds" in names
    assert "serving" in workers
    c.close()
    mc.close()


def test_daemon_backpressure_structured_and_cancel_frees_pages(daemon):
    """Flood past queue_cap: srv_submit answers the structured overloaded
    reply (code + retry_after_s) on a connection that KEEPS working;
    submit_with_backoff eventually lands; cancel frees pages."""
    from paddle_tpu.serving import Overloaded, ServingClient
    d, _ = daemon
    c = ServingClient(*d.address)
    rs = np.random.RandomState(7)
    rids, refused = [], 0
    for _ in range(12):
        try:
            rids.append(c.submit(rs.randint(0, VOCAB, 5), 80))
        except Overloaded as e:
            refused += 1
            assert e.retry_after_s > 0
    assert refused > 0 and rids             # both sides of the cap seen
    # the SAME connection still serves (structured reply, not a hangup)
    assert c.serving_stats()["queue_depth"] > 0
    # backoff-submit rides out the overload window
    late = c.submit_with_backoff(rs.randint(0, VOCAB, 5), 3)
    # cancel everything in flight; pages must all come home
    for rid in rids:
        c.cancel(rid)
    _drain(c, late)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        st = c.serving_stats()
        if st["pages_used"] == 0 and st["slots_live"] == 0:
            break
        time.sleep(0.05)
    assert st["pages_used"] == 0 and st["pages_reserved"] == 0
    c.close()


def test_daemon_structured_validation_errors(daemon):
    """Malformed submissions come back as code=invalid_argument replies
    (raised client-side as ValueError), unknown rids as not_found."""
    from paddle_tpu.serving import ServingClient
    d, _ = daemon
    c = ServingClient(*d.address)
    with pytest.raises(ValueError, match="empty prompt"):
        c.submit([], 5)
    with pytest.raises(ValueError, match="max_new"):
        c.submit([3, 5], 0)
    with pytest.raises(ValueError, match="max_len"):
        c.submit(list(range(MAX_LEN)), 5)
    with pytest.raises(KeyError):
        c.poll(999999)
    c.close()


def test_submit_idempotent_across_transport_retry(daemon):
    """srv_submit rides the transport's at-least-once retry: replaying the
    SAME submit_key (a lost-reply resend) returns the original rid instead
    of admitting a duplicate generation."""
    d, _ = daemon
    mc = MasterClient(*d.address)
    req = {"op": "srv_submit", "prompt": [3, 5, 7], "max_new": 4,
           "submit_key": "retry-test-key"}
    r1 = mc._call(dict(req))
    r2 = mc._call(dict(req))            # the replay
    assert r1["ok"] and r2["ok"] and r1["rid"] == r2["rid"]
    fresh = mc._call({"op": "srv_submit", "prompt": [3, 5, 7],
                      "max_new": 4, "submit_key": "another-key"})
    assert fresh["rid"] != r1["rid"]
    mc.close()


def test_submit_replay_during_drain_returns_original_rid(daemon):
    """A lost-reply replay of an ALREADY-admitted submit must learn its
    rid even while the daemon is draining — its finished result is exactly
    what the drain window waits for the client to collect. Only NEW work
    gets the structured draining refusal."""
    d, _ = daemon
    req = {"op": "srv_submit", "prompt": [3, 5, 7], "max_new": 4,
           "submit_key": "drain-replay-key"}
    first = d._srv_submit(dict(req))
    assert first["ok"]
    d._draining.set()
    try:
        replay = d._srv_submit(dict(req))
        assert replay.get("ok") and replay["rid"] == first["rid"]
        fresh = d._srv_submit({"op": "srv_submit", "prompt": [3, 5, 7],
                               "max_new": 4, "submit_key": "drain-new-key"})
        assert not fresh["ok"] and fresh["code"] == "overloaded"
    finally:
        d._draining.clear()


def test_abandoned_stream_cancels_server_side(daemon):
    """Breaking out of stream() mid-generation must cancel the request on
    the server — an abandoned consumer must not pin its slot and reserved
    pages for the rest of the budget."""
    from paddle_tpu.serving import ServingClient
    d, _ = daemon
    c = ServingClient(*d.address)
    gen = c.stream([3, 5, 7], 10_000)   # budget far beyond the test
    next(gen)                            # at least one token arrived
    gen.close()                          # GeneratorExit -> srv_cancel
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        st = c.serving_stats()
        if st["slots_live"] == 0 and st["pages_used"] == 0:
            break
        time.sleep(0.05)
    assert st["slots_live"] == 0 and st["pages_used"] == 0, st
    with d.engine._lock:
        reasons = [r.reason for r in d.engine._recs.values() if r.done]
    assert "cancelled" in reasons        # freed by the cancel, not by length
    c.close()


def test_stream_surfaces_cancellation(daemon):
    """A server-side cancel must raise out of stream()/generate(), never
    read as a short-but-normal completion. The cancel fires synchronously
    at submit time (before the scheduler can touch the queued record) —
    a polling killer thread used to lose the race on a compile-warm
    session, where all ~12 segments finish inside one 50 ms poll."""
    from paddle_tpu.serving import ServingClient
    d, _ = daemon
    c = ServingClient(*d.address)
    orig = d.engine.submit

    def submit_then_cancel(*a, **kw):
        rid = orig(*a, **kw)
        assert d.engine.cancel(rid) is True   # queued -> cancel always wins
        return rid

    d.engine.submit = submit_then_cancel
    try:
        with pytest.raises(RuntimeError, match="cancelled"):
            list(c.stream(np.random.RandomState(3).randint(0, VOCAB, 5),
                          100))
    finally:
        d.engine.submit = orig
    c.close()


def test_stop_does_not_deadlock_with_conn_counting_handler():
    """Regression: stop() used to hold _srv_lock across ptms_stop (which
    drains handler threads); a handler inside active_connections() —
    exactly what srv_stats does — blocked on that lock forever, hanging
    every daemon shutdown that raced a stats poll."""
    import threading

    srv = MasterServer()
    entered = threading.Event()

    def slow_conn_handler(req):
        entered.set()
        time.sleep(0.3)                  # let stop() start first
        return {"ok": True, "conns": srv.active_connections()}

    srv.register_op("conn_probe", slow_conn_handler)
    srv.start()
    mc = MasterClient(*srv.address)
    def probe():
        try:
            mc._call({"op": "conn_probe"})
        except ConnectionError:
            pass                         # stop() may win the race; fine

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    assert entered.wait(10.0)
    stopper = threading.Thread(target=srv.stop, daemon=True)
    stopper.start()
    stopper.join(timeout=20.0)
    assert not stopper.is_alive(), "MasterServer.stop() deadlocked"
    mc.close()
    t.join(timeout=10.0)


def test_register_op_rejects_shadowing(model_and_params):
    """The op table is a wire contract: built-ins and earlier
    registrations cannot be silently replaced."""
    srv = MasterServer()
    srv.register_op("my_op", lambda req: {"ok": True})
    with pytest.raises(ValueError, match="already registered"):
        srv.register_op("my_op", lambda req: {"ok": True})
    with pytest.raises(ValueError, match="already registered"):
        srv.register_op("get_task", lambda req: {"ok": True})


@pytest.mark.slow
def test_serve_cli_subprocess_e2e(tmp_path):
    """`paddle_tpu serve` as a real subprocess daemon: parseable SERVING
    line, exact greedy over the wire against the same seed's weights,
    graceful SIGTERM with an obs dump."""
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.serving import ServingClient
    obs_out = str(tmp_path / "serve_obs.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu", "serve",
         "--vocab", str(VOCAB), "--d_model", str(D), "--n_heads", str(H),
         "--n_layers", str(L), "--max_len", str(MAX_LEN), "--seed", "0",
         "--slots", "2", "--segment", "8", "--page_block", "8",
         "--cache_bucket", "32", "--obs_out", obs_out],
        stdout=subprocess.PIPE, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        line = p.stdout.readline()
        m = re.match(r"SERVING (\S+) (\d+)", line)
        assert m, f"bad address line: {line!r}"
        host, port = m.group(1), int(m.group(2))
        c = ServingClient(host, port, call_timeout=60.0)
        model = TransformerLM(VOCAB, d_model=D, n_heads=H, n_layers=L,
                              max_len=MAX_LEN)
        params = model.init(jax.random.PRNGKey(0))
        prompt = np.random.RandomState(1).randint(0, VOCAB, 11)
        out = c.generate(prompt, 15)
        want = np.asarray(model.generate_cached(
            params, jnp.asarray(prompt[None]), steps=15))[0, 11:]
        np.testing.assert_array_equal(out, want)
        c.close()
    finally:
        p.send_signal(signal.SIGTERM)
        assert p.wait(timeout=60) == 0
    assert os.path.exists(obs_out)
