"""Paged KV-cache serving (paddle_tpu/serving/paged.py + engine.py): the
cache is a shared page pool + per-request block tables instead of per-slot
max_len rows. Contracts under test:

* EXACTNESS — greedy tokens through the paged pool are bit-equal to solo
  decode (generate_cached / generate_fused at the same kv_dtype), mixed
  lengths, incl. int8 KV;
* RECLAMATION — finished/cancelled/timed-out requests return their pages
  immediately and the freed slot re-admits queued work;
* the paged read's kernel and dense routes share one formulation
  (ops/pallas_kernels.paged_decode_attention);
* validation hardening — malformed requests die structured at submit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import obs
from paddle_tpu.ops import pallas_kernels as pk
from paddle_tpu.serving import (ContinuousBatcher, Overloaded, PagedBatcher,
                                Request, ServingEngine)

VOCAB, D, H, L, MAX_LEN = 97, 32, 4, 2, 128


@pytest.fixture(scope="module")
def model_and_params(paged_model_and_params):
    """The session-shared model (conftest.py): pools built over the same
    instance share traced admission/segment executables per shape family
    instead of re-tracing per test (ROADMAP item 5)."""
    return paged_model_and_params


def _solo(model, params, prompt, steps, _bucket=12):
    """Solo-decode reference, steps padded onto shared scan compiles
    (greedy is prefix-stable; same trick as test_serving.py)."""
    padded = min(-(-steps // _bucket) * _bucket,
                 model.max_len - len(prompt))
    out = model.generate_cached(params, jnp.asarray(prompt[None]),
                                steps=padded)
    return np.asarray(out)[0, len(prompt):len(prompt) + steps]


def test_paged_matches_solo_decode(model_and_params):
    """The tentpole contract: mixed prompt/gen lengths through the paged
    pool, every request's greedy continuation token-for-token equal to
    decoding it alone — and every page back in the free list after."""
    model, params = model_and_params
    rs = np.random.RandomState(3)
    reqs = []
    for rid in range(9):          # more requests than slots -> churn
        plen = int(rs.randint(3, 40))
        gen = int(rs.randint(1, 37))
        reqs.append(Request(rid, rs.randint(0, VOCAB, plen), gen))
    b = PagedBatcher(model, params, slots=4, segment=8, page_block=8,
                     cache_bucket=32)
    got = b.serve(reqs)
    assert sorted(got) == [r.rid for r in reqs]
    for r in reqs:
        want = _solo(model, params, r.prompt, r.max_new)
        np.testing.assert_array_equal(
            got[r.rid], want,
            err_msg=f"request {r.rid} (prompt {len(r.prompt)}, gen "
                    f"{r.max_new}) diverged under the paged cache")
    assert b.pool.pages_used == 0 and b.pool.reserved == 0
    assert 0 < b.pool.peak_pages_used <= b.pool.capacity_pages


def test_paged_matches_pinned_batcher(model_and_params):
    """Paged and pinned pools run the same masked-softmax read: identical
    outputs on an identical workload (the memory manager is invisible)."""
    model, params = model_and_params
    rs = np.random.RandomState(9)
    reqs = [Request(i, rs.randint(0, VOCAB, int(rs.randint(3, 30))),
                    int(rs.randint(1, 25))) for i in range(5)]
    pinned = ContinuousBatcher(model, params, slots=3, segment=8,
                               cache_bucket=32, schedule="fifo").serve(
        [Request(r.rid, r.prompt.copy(), r.max_new) for r in reqs])
    paged = PagedBatcher(model, params, slots=3, segment=8, page_block=8,
                         cache_bucket=32, schedule="fifo").serve(
        [Request(r.rid, r.prompt.copy(), r.max_new) for r in reqs])
    for r in reqs:
        np.testing.assert_array_equal(paged[r.rid], pinned[r.rid])


def test_paged_int8_matches_solo_int8(model_and_params):
    """Quantized-KV exactness carries over: int8 paged tokens equal SOLO
    decode at the same kv_dtype (batching and paging add no error)."""
    model, params = model_and_params
    rs = np.random.RandomState(13)
    reqs = [Request(rid, rs.randint(0, VOCAB, int(rs.randint(3, 30))),
                    int(rs.randint(1, 25))) for rid in range(3)]
    b = PagedBatcher(model, params, slots=2, segment=8, page_block=8,
                     cache_bucket=32, kv_dtype="int8")
    got = b.serve(reqs)
    for r in reqs:
        want = np.asarray(model.generate_fused(
            params, jnp.asarray(r.prompt[None]), steps=r.max_new,
            kv_dtype="int8"))[0, len(r.prompt):]
        np.testing.assert_array_equal(got[r.rid], want,
                                      err_msg=f"request {r.rid}")


def test_paged_eos_and_small_pool_queueing(model_and_params):
    """EOS truncation works through pages, and a pool too small for every
    request at once queues the tail (admission control) without changing
    anyone's tokens."""
    model, params = model_and_params
    rs = np.random.RandomState(5)
    prompt = rs.randint(0, VOCAB, 9)
    full = _solo(model, params, prompt, 24)
    eos = int(full[7])
    # pool sized so ~one request fits at a time: (9 + 24 + 8 - 1) / 8 -> 5
    # pages; 8 usable pages hold one live request + change
    b = PagedBatcher(model, params, slots=3, segment=8, page_block=8,
                     pages=9, cache_bucket=32)
    reqs = [Request(0, prompt, 24, eos_id=eos),
            Request(1, rs.randint(0, VOCAB, 7), 11),
            Request(2, rs.randint(0, VOCAB, 5), 9)]
    got = b.serve(reqs)
    first_hit = int(np.nonzero(full == eos)[0][0])
    np.testing.assert_array_equal(got[0], full[:first_hit])
    for r in reqs[1:]:
        np.testing.assert_array_equal(
            got[r.rid], _solo(model, params, r.prompt, r.max_new))
    assert b.pool.pages_used == 0


def test_admission_wave_cannot_overcommit_pool(model_and_params):
    """Regression: fits() must count pages the SAME admission wave already
    claimed. Two free slots + two requests each reserving 5 pages against
    an 8-page pool used to both pass fits(5) (pool.reserved only updates
    inside pool.admit), then exhaust the free list mid-decode with
    'page pool exhausted past its reservations'. Now the second queues,
    both finish exactly, and the reservation invariant holds throughout."""
    model, params = model_and_params
    rs = np.random.RandomState(41)
    reqs = [Request(0, rs.randint(0, VOCAB, 8), 25),
            Request(1, rs.randint(0, VOCAB, 8), 25)]   # 5 pages each
    b = PagedBatcher(model, params, slots=2, segment=8, page_block=8,
                     pages=9, cache_bucket=32)         # capacity 8 < 2*5
    got = b.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(
            got[r.rid], _solo(model, params, r.prompt, r.max_new))
    assert b.pool.pages_used == 0
    assert b.pool.peak_pages_used <= b.pool.capacity_pages
    # engine path shares the fix
    eng = ServingEngine(model, params, slots=2, segment=8, page_block=8,
                        pages=9, cache_bucket=32, queue_cap=4)
    rids = [eng.submit(r.prompt, r.max_new) for r in reqs]
    eng.step()
    assert eng.pool.reserved <= eng.pool.capacity_pages
    while not all(eng.poll(r)[1] for r in rids):
        eng.step()
        assert eng.pool.reserved <= eng.pool.capacity_pages
    assert eng.pool.pages_used == 0


def test_paged_attention_routes_agree(model_and_params):
    """paged_decode_attention: the scalar-prefetch kernel (pages assembled
    in VMEM) vs the dense gather route — same formulation, f32/int8 —
    and the dense route is bit-equal to the dense-ROW decode_attention on
    the gathered cache (the pinned-parity building block)."""
    del model_and_params
    B, Hh, Dh, bs, NB, P = 3, 4, 16, 8, 4, 14
    rs = np.random.RandomState(0)
    k_pool = jnp.asarray(rs.randn(P, bs, Hh, Dh), jnp.float32)
    v_pool = jnp.asarray(rs.randn(P, bs, Hh, Dh), jnp.float32)
    tables = jnp.asarray(np.stack(
        [rs.choice(np.arange(1, P), NB, replace=False) for _ in range(B)]),
        jnp.int32)
    q = jnp.asarray(rs.randn(B, Hh, Dh), jnp.float32)
    pos = jnp.asarray([3, 17, 30], jnp.int32)
    dense = pk.paged_decode_attention(q, k_pool, v_pool, tables, pos,
                                      route="dense")
    kern = pk.paged_decode_attention(q, k_pool, v_pool, tables, pos,
                                     route="kernel", interpret=True)
    np.testing.assert_allclose(np.asarray(kern), np.asarray(dense),
                               rtol=2e-6, atol=2e-6)
    row = pk.decode_attention(q, pk.gather_pages(k_pool, tables),
                              pk.gather_pages(v_pool, tables), pos,
                              route="dense")
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(row))
    k8, ks = pk.quantize_kv(k_pool)
    v8, vs = pk.quantize_kv(v_pool)
    d8 = pk.paged_decode_attention(q, k8, v8, tables, pos, k_scale=ks,
                                   v_scale=vs, route="dense")
    k8o = pk.paged_decode_attention(q, k8, v8, tables, pos, k_scale=ks,
                                    v_scale=vs, route="kernel",
                                    interpret=True)
    np.testing.assert_allclose(np.asarray(k8o), np.asarray(d8),
                               rtol=2e-6, atol=2e-6)


def test_validation_hardening(model_and_params):
    """Malformed requests die AT SUBMIT with precise errors (not as shape
    errors deep in prefill): max_new <= 0, empty prompt, prompt past the
    page budget — for both batchers and the engine."""
    model, params = model_and_params
    b = PagedBatcher(model, params, slots=2, segment=8, page_block=8,
                     cache_bucket=32)
    with pytest.raises(ValueError, match="max_new"):
        b.serve([Request(0, np.array([3, 5], np.int32), 0)])
    with pytest.raises(ValueError, match="empty prompt"):
        b.serve([Request(0, np.zeros((0,), np.int32), 4)])
    pinned = ContinuousBatcher(model, params, slots=2, segment=8,
                               cache_bucket=32)
    with pytest.raises(ValueError, match="max_new"):
        pinned.serve([Request(1, np.array([3], np.int32), -2)])
    # page budget: a 6-usable-page pool (48 positions) cannot ever hold
    # prompt 60 — rejected structured at submit, nothing queued
    tiny = PagedBatcher(model, params, slots=2, segment=8, page_block=8,
                        pages=7, cache_bucket=32)
    with pytest.raises(ValueError, match="pages"):
        tiny.serve([Request(2, np.arange(60, dtype=np.int32) % VOCAB, 4)])
    eng = ServingEngine(model, params, slots=2, segment=8, page_block=8,
                        cache_bucket=32, queue_cap=2)
    with pytest.raises(ValueError, match="max_new"):
        eng.submit(np.array([3], np.int32), 0)


def test_engine_cancel_frees_pages_and_readmits(model_and_params):
    """Mid-flight cancel: the request finalizes with reason=cancelled, its
    pages return at the next segment boundary, and the freed slot admits
    queued work — driven deterministically via engine.step()."""
    model, params = model_and_params
    rs = np.random.RandomState(21)
    eng = ServingEngine(model, params, slots=1, segment=8, page_block=8,
                        cache_bucket=32, queue_cap=4)
    long_rid = eng.submit(rs.randint(0, VOCAB, 9), 100)
    short_prompt = rs.randint(0, VOCAB, 7)
    short_rid = eng.submit(short_prompt, 9)
    eng.step()                       # admit long (slot 0) + one segment
    toks, done, _ = eng.poll(long_rid)
    assert toks and not done
    used_live = eng.pool.pages_used
    assert used_live > 0
    assert eng.poll(short_rid)[0] == []          # still queued (1 slot)
    assert eng.cancel(long_rid) is True
    eng.step()                       # reap: free pages, admit the short
    toks, done, reason = eng.poll(long_rid)
    assert done and reason == "cancelled"
    eng.step()
    while not eng.poll(short_rid)[1]:
        eng.step()
    toks, done, reason = eng.poll(short_rid)
    assert done and reason == "length"
    np.testing.assert_array_equal(
        np.asarray(toks, np.int32), _solo(model, params, short_prompt, 9))
    assert eng.pool.pages_used == 0 and eng.pool.reserved == 0
    # cancel of a finished request is a no-op, not an error
    assert eng.cancel(short_rid) is False


def test_engine_timeout_frees_pages(model_and_params):
    """Deadlines: a queued request times out without touching the pool; a
    LIVE request's timeout frees slot + pages (fake clock, no sleeps)."""
    model, params = model_and_params
    rs = np.random.RandomState(23)
    t = [0.0]
    eng = ServingEngine(model, params, slots=1, segment=8, page_block=8,
                        cache_bucket=32, queue_cap=4, clock=lambda: t[0])
    live = eng.submit(rs.randint(0, VOCAB, 9), 100, timeout_s=50.0)
    queued = eng.submit(rs.randint(0, VOCAB, 5), 10, timeout_s=10.0)
    eng.step()                                   # live admitted
    assert eng.pool.pages_used > 0
    t[0] = 20.0                                  # queued deadline passes
    eng.step()
    assert eng.poll(queued)[1:] == (True, "timeout")
    t[0] = 60.0                                  # live deadline passes
    eng.step()
    assert eng.poll(live)[1:] == (True, "timeout")
    assert eng.pool.pages_used == 0 and eng.pool.reserved == 0


def test_engine_backpressure_structured(model_and_params):
    """Queue-cap admission control raises the STRUCTURED Overloaded (with
    a retry hint) — and the engine keeps serving afterwards."""
    model, params = model_and_params
    rs = np.random.RandomState(29)
    eng = ServingEngine(model, params, slots=1, segment=8, page_block=8,
                        cache_bucket=32, queue_cap=1)
    first = eng.submit(rs.randint(0, VOCAB, 5), 3)   # fills the 1-deep queue
    with pytest.raises(Overloaded) as ei:
        eng.submit(rs.randint(0, VOCAB, 5), 3)
    assert ei.value.retry_after_s > 0
    while not eng.poll(first)[1]:                    # still serving after
        eng.step()
    second = eng.submit(rs.randint(0, VOCAB, 5), 3)  # queue drained: admits
    while not eng.poll(second)[1]:
        eng.step()
    assert eng.pool.pages_used == 0


def test_engine_dispatch_failure_fails_loudly(model_and_params):
    """A dispatch blowing up must not leave a daemon that LOOKS alive:
    outstanding requests finalize with reason=error (pollers see done, not
    an infinite hang) and new submissions carry the cause."""
    import time as _time
    model, params = model_and_params
    rs = np.random.RandomState(37)
    eng = ServingEngine(model, params, slots=1, segment=8, page_block=8,
                        cache_bucket=32, queue_cap=4)

    def boom(live):
        raise RuntimeError("synthetic device failure")
    eng.pool.run_segment = boom
    eng.start()
    try:
        rid = eng.submit(rs.randint(0, VOCAB, 5), 10)
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline and not eng.poll(rid)[1]:
            _time.sleep(0.02)
        toks, done, reason = eng.poll(rid)
        assert done and reason == "error"
        with pytest.raises(RuntimeError, match="synthetic device failure"):
            eng.submit(rs.randint(0, VOCAB, 5), 10)
    finally:
        eng.stop()


def test_engine_slo_metrics_and_gauges(model_and_params):
    """TTFT/TPOT histograms and the queue/page gauges land in the metric
    registry (the obs summary the acceptance criterion names)."""
    model, params = model_and_params
    rs = np.random.RandomState(31)
    reg = obs.MetricsRegistry()
    with obs.ObsSession(registry=reg).installed():
        eng = ServingEngine(model, params, slots=2, segment=8, page_block=8,
                            cache_bucket=32, queue_cap=8)
        rids = [eng.submit(rs.randint(0, VOCAB, int(rs.randint(3, 20))),
                           int(rs.randint(2, 20))) for _ in range(4)]
        while not all(eng.poll(r)[1] for r in rids):
            eng.step()
    samples = reg.collect()
    names = {s["name"] for s in samples}
    assert "serving.ttft_seconds" in names
    assert "serving.tpot_seconds" in names
    assert "serving.page_occupancy" in names
    done = [s for s in samples if s["name"] == "serving.requests_total"]
    assert sum(s["value"] for s in done) == len(rids)
    occ = [s["value"] for s in samples
           if s["name"] == "serving.page_occupancy"]
    assert all(0.0 <= v <= 1.0 for v in occ)
