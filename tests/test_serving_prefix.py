"""Prefix-shared paged KV-cache (serving/prefix.py + paged.py + engine.py):
requests sharing a prompt prefix share refcounted pages copy-on-write
through a radix index, admission prefills only the non-shared suffix, and
the engine schedules slots weighted-fair across tenant SLO classes.

Contracts under test:

* EXACTNESS — greedy tokens equal SOLO decode for every hit/miss/
  partial-hit interleaving (full hit, partial-block hit, same-wave
  sharing then divergence, eviction-then-readmit, int8 KV);
* RECLAMATION — refcounts never underflow, cancel mid-flight with shared
  pages drains cleanly, and clear_prefix_cache() returns the pool to
  pages_used == 0;
* SCHEDULING — weighted-fair deficit slot assignment serves interactive
  ahead of earlier-queued batch work without idling slots;
* VALIDATION — tenant labels (bounded cardinality) and declared
  prefix_len die structured at submit.

Dims are shared with tests/test_serving_paged.py (same model family and
pool shapes), so the session compile-cache fixture reuses its traced
executables.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import obs
from paddle_tpu.serving import (PagedBatcher, PrefixIndex, Request,
                                ServingEngine)

VOCAB, D, H, L, MAX_LEN = 97, 32, 4, 2, 128
BS = 8                      # page_block — one trie level per 8 tokens


@pytest.fixture(scope="module")
def model_and_params(paged_model_and_params):
    """The session-shared model (conftest.py): dims are shared with
    test_serving_paged.py, and the per-model-instance program cache in
    serving/paged.py now shares TRACED executables across both files,
    not just XLA compiles (ROADMAP item 5)."""
    return paged_model_and_params


def _solo(model, params, prompt, steps, kv_dtype=None, _bucket=12):
    """Solo-decode reference (steps padded onto shared scan compiles —
    greedy is prefix-stable; the test_serving_paged.py trick)."""
    if kv_dtype is not None:
        out = model.generate_fused(params, jnp.asarray(prompt[None]),
                                   steps=steps, kv_dtype=kv_dtype)
        return np.asarray(out)[0, len(prompt):]
    padded = min(-(-steps // _bucket) * _bucket,
                 model.max_len - len(prompt))
    out = model.generate_cached(params, jnp.asarray(prompt[None]),
                                steps=padded)
    return np.asarray(out)[0, len(prompt):len(prompt) + steps]


def _batcher(model, params, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("segment", 8)
    kw.setdefault("page_block", BS)
    kw.setdefault("cache_bucket", 32)
    kw.setdefault("prefix_cache", True)
    return PagedBatcher(model, params, **kw)


def _assert_refs_drained(index):
    """Every trie refcount is back to zero (no leaks, no underflow — the
    release assert inside PrefixIndex guards the underflow side)."""
    stack = [index.root]
    while stack:
        n = stack.pop()
        stack.extend(n.children.values())
        if n is not index.root:
            assert n.refs == 0, f"leaked ref on node {n.key[:3]}..."


# -- the radix index itself (pure host) ---------------------------------

def test_prefix_index_radix_unit():
    idx = PrefixIndex(4, page_bytes=100.0, half_life=2)
    toks = list(range(13))                    # 3 full blocks + tail [12]
    m = idx.match(toks, len(toks) - 1)
    assert m.shared_len == 0 and not m.nodes
    # insert the path root -> [0..3] -> [4..7] (pages 5, 6) + tail (8..11)
    n0, created0 = idx.insert_full(idx.root, tuple(toks[0:4]), 5)
    n1, created1 = idx.insert_full(n0, tuple(toks[4:8]), 6)
    assert created0 and created1 and idx.total_pages == 2
    dup, created = idx.insert_full(idx.root, tuple(toks[0:4]), 99)
    assert dup is n0 and not created          # dedup keeps the first page
    p = idx.insert_partial(n1, tuple(toks[8:12]), 7, owner=3)
    assert p is not None and idx.total_pages == 2   # owner-live: not owned
    # match caps at limit: limit 9 allows 2 full blocks + 1 tail token
    m = idx.match(toks, 9)
    assert [n.page for n in m.nodes] == [5, 6]
    assert m.partial is p and m.partial_len == 1 and m.shared_len == 9
    # pin + ledger: refs block eviction; partials never pin (hits copy)
    idx.acquire(m)
    assert n0.refs == 1 and n1.refs == 1 and idx.hits == 1
    assert idx.evict_one() is None            # all full nodes pinned,
    #                                           partial owner still live
    idx.adopt(p)                              # owner slot freed
    assert idx.total_pages == 3
    assert idx.evict_one() == 7               # the only evictable entry
    idx.release(m.nodes)
    _assert_refs_drained(idx)
    # decayed measured reuse: n1 was credited at tick 0; advance ticks and
    # credit n0 again — n1 (stale leaf) must evict before n0's subtree
    idx.tick += 10
    idx._credit(n0, 100.0)
    assert idx.evict_one() == 6               # n1: cold leaf, decayed
    assert idx.evict_one() == 5               # now n0 is a leaf
    assert idx.total_pages == 0 and idx.evictions == 3


# -- exactness across interleavings -------------------------------------

def test_full_hit_matches_solo(model_and_params):
    """Warm the index with a miss, then replay the same prompt (full hit,
    one-token suffix): tokens equal solo decode, the second admission
    prefills ~nothing, and page dedup shares the prompt blocks."""
    model, params = model_and_params
    rs = np.random.RandomState(3)
    b = _batcher(model, params)
    prompt = rs.randint(0, VOCAB, 27)
    want = _solo(model, params, prompt, 11)
    np.testing.assert_array_equal(
        b.serve([Request(0, prompt.copy(), 11)])[0], want)
    st = b.pool.prefix_stats()
    assert st["prefix_misses"] == 1 and st["prefix_hits"] == 0
    cold_prefill = b.pool.prefill_tokens_total
    np.testing.assert_array_equal(
        b.serve([Request(1, prompt.copy(), 11)])[1], want)
    st = b.pool.prefix_stats()
    assert st["prefix_hits"] == 1
    # the hit prefilled only the uncached tail (<= one page + the final
    # token), not the whole prompt again
    assert b.pool.prefill_tokens_total - cold_prefill <= BS
    _assert_refs_drained(b.pool.index)


def test_partial_block_hit_cow(model_and_params):
    """A prompt diverging MID-block from a cached one: the full blocks
    share in place, the stored partial page is copied before the suffix
    appends (CoW), and tokens stay exact for both."""
    model, params = model_and_params
    rs = np.random.RandomState(5)
    b = _batcher(model, params, slots=2)
    shared = rs.randint(0, VOCAB, 21)         # 2 full blocks + 5-token tail
    a = Request(0, shared.copy(), 9)          # stores the tail as a partial
    b.serve([a])
    # diverges after 19 shared tokens: 2 full-block hits + 3-token
    # partial match into A's stored tail -> CoW copy
    c = Request(1, np.concatenate([shared[:19], rs.randint(0, VOCAB, 6)]),
                13)
    got = b.serve([c])
    np.testing.assert_array_equal(got[1], _solo(model, params, c.prompt, 13))
    st = b.pool.prefix_stats()
    assert st["prefix_hits"] == 1 and st["cow_copies"] >= 1
    _assert_refs_drained(b.pool.index)


def test_concurrent_admits_share_then_diverge(model_and_params):
    """Two requests sharing a prefix admitted in the SAME wave: both are
    misses (insertion is post-dispatch), but the index dedups their
    common blocks to one page set, and a third request then hits it.
    Tokens equal solo for every one of them."""
    model, params = model_and_params
    rs = np.random.RandomState(7)
    b = _batcher(model, params)
    shared = rs.randint(0, VOCAB, 16)         # exactly 2 full blocks
    reqs = [Request(0, np.concatenate([shared, rs.randint(0, VOCAB, 5)]), 10),
            Request(1, np.concatenate([shared, rs.randint(0, VOCAB, 3)]), 12)]
    got = b.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(
            got[r.rid], _solo(model, params, r.prompt, r.max_new))
    st = b.pool.prefix_stats()
    assert st["prefix_misses"] == 2
    # dedup: the 2 shared blocks exist ONCE (plus each request's partial
    # tail adopted on free) — not 4 full nodes
    assert st["prefix_nodes"] == 2 and st["prefix_partials"] == 2
    late = Request(2, np.concatenate([shared, rs.randint(0, VOCAB, 7)]), 8)
    got2 = b.serve([late])
    np.testing.assert_array_equal(
        got2[2], _solo(model, params, late.prompt, 8))
    assert b.pool.prefix_stats()["prefix_hits"] == 1


def test_eviction_then_readmit(model_and_params):
    """A pool too small to keep the whole cache: cold entries evict
    (measured-reuse order) to make room, a later replay of the evicted
    prompt partially misses and re-inserts, and every emission stays
    exact throughout. The worst-case-reservation invariant holds with
    index pages counted."""
    model, params = model_and_params
    rs = np.random.RandomState(11)
    b = _batcher(model, params, slots=2, pages=9)    # 8 usable pages
    pa = rs.randint(0, VOCAB, 16)                    # 2 full blocks
    want_a = _solo(model, params, pa, 8)
    np.testing.assert_array_equal(
        b.serve([Request(0, pa.copy(), 8)])[0], want_a)
    assert b.pool.index_pages == 2
    # B needs 7 owned pages: 7 + 2 cached > 8 -> eviction must free one
    pb = rs.randint(0, VOCAB, 24)
    np.testing.assert_array_equal(
        b.serve([Request(1, pb.copy(), 24)])[1],
        _solo(model, params, pb, 24))
    st = b.pool.prefix_stats()
    assert st["prefix_evictions"] >= 1
    assert b.pool.reserved == 0
    assert b.pool.pages_used == b.pool.index_pages <= b.pool.capacity_pages
    # replay A: the evicted tail of its path misses and re-inserts; the
    # surviving depth still hits. Either way: exact.
    np.testing.assert_array_equal(
        b.serve([Request(2, pa.copy(), 8)])[2], want_a)
    _assert_refs_drained(b.pool.index)
    b.pool.clear_prefix_cache()
    assert b.pool.pages_used == 0


def test_same_wave_eviction_cannot_steal_matched_pages(model_and_params):
    """Regression: plans pin their matched nodes only inside admit(), so
    an eviction triggered LATER in the same admission wave (another
    request pricing its own pages) must shield every already-planned
    match — otherwise a block table ends up pointing at a freed page
    that the very same wave re-allocates, and tokens silently diverge.
    Here B (a hit on A's cold blocks) and C (a big miss that needs an
    eviction) are planned in one wave: C must wait, not evict from under
    B, and everyone stays exact."""
    model, params = model_and_params
    rs = np.random.RandomState(17)
    b = _batcher(model, params, slots=3, pages=9, schedule="fifo")
    pa = rs.randint(0, VOCAB, 16)                 # 2 full blocks, cold
    np.testing.assert_array_equal(
        b.serve([Request(0, pa.copy(), 8)])[0], _solo(model, params, pa, 8))
    assert b.pool.index_pages == 2
    hit = Request(1, np.concatenate([pa, rs.randint(0, VOCAB, 4)]), 8)
    big = Request(2, rs.randint(0, VOCAB, 24), 16)   # needs an eviction
    got = b.serve([hit, big])
    np.testing.assert_array_equal(
        got[1], _solo(model, params, hit.prompt, 8))
    np.testing.assert_array_equal(
        got[2], _solo(model, params, big.prompt, 16))
    _assert_refs_drained(b.pool.index)


def test_int8_hits_match_solo_int8(model_and_params):
    """Quantized-KV prefix sharing: full and partial hits equal SOLO
    decode at kv_dtype=int8 (the hit path reads the dequantized prefix —
    the same read every decode step performs)."""
    model, params = model_and_params
    rs = np.random.RandomState(13)
    b = _batcher(model, params, slots=2, kv_dtype="int8")
    shared = rs.randint(0, VOCAB, 24)
    r0 = Request(0, np.concatenate([shared, rs.randint(0, VOCAB, 5)]), 12)
    got = b.serve([r0])
    np.testing.assert_array_equal(
        got[0], _solo(model, params, r0.prompt, 12, kv_dtype="int8"))
    r1 = Request(1, r0.prompt.copy(), 10)                       # full hit
    r2 = Request(2, np.concatenate([shared[:20],
                                    rs.randint(0, VOCAB, 6)]), 9)  # partial
    got2 = b.serve([r1, r2])
    np.testing.assert_array_equal(
        got2[1], _solo(model, params, r1.prompt, 10, kv_dtype="int8"))
    np.testing.assert_array_equal(
        got2[2], _solo(model, params, r2.prompt, 9, kv_dtype="int8"))
    assert b.pool.prefix_stats()["prefix_hits"] == 2


# -- engine: reclamation, scheduling, validation -------------------------

def test_engine_cancel_mid_flight_with_shared_pages(model_and_params):
    """Cancel a request READING shared prefix pages mid-decode: refcounts
    release (never underflow), its owned pages free, the survivors' reads
    are untouched, and the drained pool holds exactly the cached pages —
    which clear_prefix_cache() then returns to the free list."""
    model, params = model_and_params
    rs = np.random.RandomState(21)
    eng = ServingEngine(model, params, slots=2, segment=8, page_block=BS,
                        cache_bucket=32, queue_cap=8, prefix_cache=True)
    shared = rs.randint(0, VOCAB, 16)
    pa = np.concatenate([shared, rs.randint(0, VOCAB, 4)])
    first = eng.submit(pa, 8)
    while not eng.poll(first)[1]:
        eng.step()                      # warm the index (2 full blocks)
    assert eng.pool.index_pages >= 2
    # two hits share the cached blocks; one is cancelled mid-flight
    victim = eng.submit(np.concatenate([shared, rs.randint(0, VOCAB, 3)]),
                        100)
    survivor_prompt = np.concatenate([shared, rs.randint(0, VOCAB, 5)])
    survivor = eng.submit(survivor_prompt, 9)
    eng.step()                          # admit both (hits), one segment
    assert eng.pool.index.live_pages() == 2     # pinned by both readers
    assert eng.cancel(victim) is True
    eng.step()                          # reap: victim's pins release
    assert eng.poll(victim)[1:] == (True, "cancelled")
    while not eng.poll(survivor)[1]:
        eng.step()
    toks, done, reason = eng.poll(survivor)
    assert done and reason == "length"
    np.testing.assert_array_equal(
        np.asarray(toks, np.int32), _solo(model, params, survivor_prompt, 9))
    _assert_refs_drained(eng.pool.index)
    assert eng.pool.reserved == 0
    assert eng.pool.pages_used == eng.pool.index_pages
    eng.pool.clear_prefix_cache()
    assert eng.pool.pages_used == 0


def test_weighted_fair_deficit_scheduling(model_and_params):
    """Slot assignment is weighted-fair, not FCFS: with one slot and a
    backlog, the interactive request admits ahead of two earlier-queued
    batch requests (weight 4:1), and batch still runs afterwards
    (work-conserving). Per-request tokens are schedule-independent."""
    model, params = model_and_params
    rs = np.random.RandomState(23)
    t = [0.0]
    eng = ServingEngine(model, params, slots=1, segment=8, page_block=BS,
                        cache_bucket=32, queue_cap=8,
                        clock=lambda: (t.__setitem__(0, t[0] + 1.0),
                                       t[0])[1])
    prompts = {n: rs.randint(0, VOCAB, 9) for n in ("b1", "b2", "i1")}
    b1 = eng.submit(prompts["b1"], 8, slo="batch")
    b2 = eng.submit(prompts["b2"], 8, slo="batch")
    i1 = eng.submit(prompts["i1"], 8, slo="interactive")
    for _ in range(40):
        eng.step()
        if all(eng.poll(r)[1] for r in (b1, b2, i1)):
            break
    order = sorted((b1, b2, i1), key=lambda r: eng.timings(r)["t_first"])
    assert order[0] == i1, "interactive should pre-empt queued batch work"
    assert order[1:] == [b1, b2], "batch stays FIFO within its class"
    for rid, name in ((b1, "b1"), (b2, "b2"), (i1, "i1")):
        np.testing.assert_array_equal(
            np.asarray(eng.poll(rid)[0], np.int32),
            _solo(model, params, prompts[name], 8))
    st = eng.stats()
    assert st["queue_interactive"] == 0 and st["queue_batch"] == 0


def test_tenant_and_prefix_validation(model_and_params):
    """The validation-hardening satellite: tenant labels violating the
    bounded-cardinality contract, unknown SLO classes, and a declared
    prefix longer than the prompt all die structured at submit — engine,
    batcher, and daemon handler alike."""
    model, params = model_and_params
    eng = ServingEngine(model, params, slots=2, segment=8, page_block=BS,
                        cache_bucket=32, queue_cap=4, max_tenants=2)
    p = np.arange(5, dtype=np.int32) % VOCAB
    with pytest.raises(ValueError, match="cardinality"):
        eng.submit(p, 4, tenant="a/b/c")          # path-like label value
    with pytest.raises(ValueError, match="cardinality"):
        eng.submit(p, 4, tenant="x" * 80)         # oversized label value
    with pytest.raises(ValueError, match="slo"):
        eng.submit(p, 4, slo="turbo")
    with pytest.raises(ValueError, match="prefix_len"):
        eng.submit(p, 4, prefix_len=6)            # longer than the prompt
    eng.submit(p, 4, tenant="t1")
    eng.submit(p, 4, tenant="t2")
    with pytest.raises(ValueError, match="tenant"):
        eng.submit(p, 4, tenant="t3")             # past the series budget
    b = _batcher(model, params, slots=2)
    with pytest.raises(ValueError, match="prefix_len"):
        b.serve([Request(0, p.copy(), 4, prefix_len=99)])
    from paddle_tpu.serving import ServingDaemon
    d = ServingDaemon(ServingEngine(model, params, slots=2, segment=8,
                                    page_block=BS, cache_bucket=32))
    r = d._do_submit({"prompt": [3, 5], "max_new": 4, "tenant": "a b"})
    assert r["ok"] is False and r["code"] == "invalid_argument"
    r = d._do_submit({"prompt": [3, 5], "max_new": 4, "prefix_len": 9})
    assert r["ok"] is False and r["code"] == "invalid_argument"


def test_prefix_metrics_and_tenant_labels(model_and_params):
    """The serving.prefix_* catalogue entries and per-tenant labels land
    in a live registry with the documented label keys."""
    model, params = model_and_params
    rs = np.random.RandomState(31)
    reg = obs.MetricsRegistry()
    with obs.ObsSession(registry=reg).installed():
        eng = ServingEngine(model, params, slots=2, segment=8,
                            page_block=BS, cache_bucket=32, queue_cap=8,
                            prefix_cache=True)
        prompt = rs.randint(0, VOCAB, 18)
        # sequential waves so the second admission HITS the first's blocks
        r0 = eng.submit(prompt.copy(), 6, tenant="acme")
        while not eng.poll(r0)[1]:
            eng.step()
        r1 = eng.submit(prompt.copy(), 6, tenant="acme")
        while not eng.poll(r1)[1]:
            eng.step()
    samples = reg.collect()
    by_name = {}
    for s in samples:
        by_name.setdefault(s["name"], []).append(s)
    assert "serving.prefix_misses_total" in by_name
    assert "serving.prefix_hits_total" in by_name
    assert "serving.prefix_pages_shared" in by_name
    assert any(s["labels"].get("tenant") == "acme"
               for s in by_name["serving.prefix_hits_total"])
    done = by_name["serving.requests_total"]
    assert all(s["labels"].get("tenant") == "acme" for s in done)
