"""The chaos-proved serving router (serving/router.py): disaggregated
prefill/decode placement from health TRENDS, structured backpressure
aggregation, re-route on eviction (graceful leave AND kill -9), router
restart recovery off the submit_key replay ladder — always against the
bar that client-visible greedy tokens bit-equal solo single-engine decode
with zero lost or duplicated tokens."""

import contextlib
import os
import re
import signal
import socket
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import faults
from paddle_tpu.runtime import native_available
from paddle_tpu.runtime.master_service import MasterClient

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native host runtime unavailable")

VOCAB, D, H, L, MAX_LEN = 97, 32, 4, 2, 128
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def model_and_params():
    from paddle_tpu.models import TransformerLM
    model = TransformerLM(VOCAB, d_model=D, n_heads=H, n_layers=L,
                          max_len=MAX_LEN)
    return model, model.init(jax.random.PRNGKey(0))


def _ref(model, params, prompt, max_new):
    """Solo single-engine greedy decode — the parity bar every routed
    stream is held to, whatever happened to its placement."""
    return np.asarray(model.generate_cached(
        params, jnp.asarray(np.asarray(prompt)[None]),
        steps=max_new))[0, len(prompt):]


@contextlib.contextmanager
def _fleet(model, params, n_decode=2, prefill=False, port=0,
           prefill_prefix_cache=False, **eng_kw):
    """Router + n in-process decode daemons (+ optional prefill worker),
    all joined, behind a private obs registry."""
    from paddle_tpu import obs
    from paddle_tpu.serving import (PagePool, PrefillDaemon, ServingDaemon,
                                    ServingEngine, ServingRouter)
    reg = obs.MetricsRegistry()
    session = obs.ObsSession(registry=reg).install()
    kw = dict(slots=2, segment=8, page_block=8, cache_bucket=32)
    kw.update(eng_kw)
    router = ServingRouter(port=port, ttl=1.0,
                           scrape_interval_s=0.1).start()
    daemons = []
    try:
        for i in range(n_decode):
            d = ServingDaemon(ServingEngine(model, params, **kw)).start()
            d.join_router(router.address, f"d{i}", role="decode")
            daemons.append(d)
        if prefill:
            pool = PagePool(model, params, slots=2, segment=kw["segment"],
                            page_block=kw["page_block"],
                            cache_bucket=kw["cache_bucket"],
                            prefix_cache=prefill_prefix_cache)
            pd = PrefillDaemon(pool).start()
            pd.join_router(router.address, "p0", role="prefill")
            daemons.append(pd)
        yield router, daemons, reg
    finally:
        for d in daemons:
            d.stop()
        router.stop()
        session.uninstall()


def _counter(reg, name, **labels):
    total = 0.0
    for s in reg.collect():
        if s["name"] == name and all(s["labels"].get(k) == v
                                     for k, v in labels.items()):
            total += s["value"]
    return total


def _throttle(daemons, delay_s=0.05):
    """Slow every decode dispatch so streams are reliably MID-flight when
    the chaos lands — a warm compile cache otherwise finishes a whole
    24-token budget faster than one poll round-trip."""
    for d in daemons:
        orig = d.engine.decode_segment

        def slow(o=orig):
            time.sleep(delay_s)
            o()
        d.engine.decode_segment = slow


def _drain_interleaved(client, work, timeout=120.0, cursors=None):
    """Round-robin poll a set of {key: rid} to completion — the cursors
    only ever advance, so any lost or duplicated token breaks parity."""
    cursors = {k: (cursors or {}).get(k, 0) for k in work}
    toks = {k: [] for k in work}
    live = set(work)
    deadline = time.monotonic() + timeout
    while live:
        assert time.monotonic() < deadline, "routed drain timed out"
        for k in list(live):
            got, done, reason = client.poll(work[k], cursors[k])
            toks[k].extend(got)
            cursors[k] += len(got)
            if done:
                assert reason in ("length", "eos"), (k, reason)
                live.discard(k)
        time.sleep(0.02)
    return {k: np.asarray(v, np.int32) for k, v in toks.items()}


def test_disaggregated_fleet_interleaved_streams_exact(model_and_params):
    """The tentpole, end to end in-process: 1 prefill + 2 decode workers
    behind the router; interleaved streams come back bit-equal to solo
    decode; KV pages actually SHIPPED (prefill ran on a different pool
    than decode); stats report the fleet shape; replies carry the
    membership epoch."""
    from paddle_tpu.serving import RouterClient
    model, params = model_and_params
    with _fleet(model, params, n_decode=2, prefill=True) as (router, ds,
                                                             reg):
        c = RouterClient(*router.address, call_timeout=60.0)
        st = c.serving_stats()
        assert st["n_decode_workers"] == 2
        assert st["n_prefill_workers"] == 1
        rs = np.random.RandomState(21)
        reqs = {i: (rs.randint(0, VOCAB, n), g)
                for i, (n, g) in enumerate([(7, 18), (11, 20), (13, 24)])}
        work = {i: c.submit(p, g) for i, (p, g) in reqs.items()}
        got = _drain_interleaved(c, work)
        for i, (p, g) in reqs.items():
            np.testing.assert_array_equal(got[i], _ref(model, params, p, g))
        # the pages went over the wire: prefill-side export counted ship
        # pages, decode-side adoption counted adopts — different pools
        assert _counter(reg, "serving.ship_pages_total") > 0
        assert _counter(reg, "serving.adopted_total") >= len(reqs)
        assert _counter(reg, "router.requests_total", outcome="ok") \
            >= len(reqs)
        assert c.last_epoch is not None       # epoch rode every reply
        c.close()


def test_prefix_hit_rate_preserved_across_the_hop(model_and_params):
    """Disaggregation must not cost the prefix cache: a second prompt
    sharing full blocks with an earlier one HITS the prefill worker's
    radix index (only its suffix re-prefills), and the exported slot
    still decodes token-exact on the far worker — shared pages ship as
    complete rows, not as references into the prefill pool."""
    from paddle_tpu.serving import RouterClient
    model, params = model_and_params
    rs = np.random.RandomState(29)
    base = rs.randint(0, VOCAB, 17).astype(np.int32)   # 2 full blocks + 1
    p2 = np.concatenate([base[:16], rs.randint(0, VOCAB, 3,
                                               dtype=np.int32)])
    with _fleet(model, params, n_decode=1, prefill=True,
                prefill_prefix_cache=True) as (router, ds, reg):
        c = RouterClient(*router.address, call_timeout=60.0)
        got1 = _drain_interleaved(c, {"a": c.submit(base, 12)})["a"]
        np.testing.assert_array_equal(got1, _ref(model, params, base, 12))
        hits0 = _counter(reg, "serving.prefix_hits_total")
        got2 = _drain_interleaved(c, {"b": c.submit(p2, 12)})["b"]
        np.testing.assert_array_equal(got2, _ref(model, params, p2, 12))
        assert _counter(reg, "serving.prefix_hits_total") > hits0
        assert _counter(reg, "serving.adopted_total") >= 2
        c.close()


def test_saturation_structured_overloaded_and_backoff_recovery(
        model_and_params):
    """Saturate BOTH decode pools: the router aggregates the structured
    refusals into one Overloaded (minimum retry_after_s hint, never a
    hang or traceback) on a connection that keeps serving, and
    submit_with_backoff rides the window out once a pool drains."""
    from paddle_tpu.serving import Overloaded, RouterClient
    model, params = model_and_params
    with _fleet(model, params, n_decode=2, queue_cap=2) as (router, ds,
                                                            reg):
        c = RouterClient(*router.address, call_timeout=60.0)
        rs = np.random.RandomState(3)
        rids, refusals = [], []
        for _ in range(16):
            try:
                rids.append(c.submit(rs.randint(0, VOCAB, 5), 80))
            except Overloaded as e:
                refusals.append(e)
        assert rids and refusals              # both sides of the cap seen
        assert all(e.retry_after_s > 0 for e in refusals)
        assert any("saturated" in str(e) for e in refusals)
        # the SAME connection still answers (structured reply, no hangup)
        assert c.serving_stats()["inflight"] >= 1
        assert _counter(reg, "router.requests_total",
                        outcome="overloaded") == len(refusals)
        for rid in rids:
            c.cancel(rid)
        late = c.submit_with_backoff(rs.randint(0, VOCAB, 5), 3)
        got = _drain_interleaved(c, {"late": late})["late"]
        assert got.size == 3
        c.close()


def test_graceful_leave_reroutes_stream_exact(model_and_params):
    """Stop the worker holding a live stream (graceful leave): the
    membership notification marks the record, the next poll re-places it
    on the survivor by re-prefilling prompt + delivered tokens, and the
    client-visible sequence is still exactly solo decode — the seam is
    invisible to cursors."""
    from paddle_tpu.serving import RouterClient
    model, params = model_and_params
    with _fleet(model, params, n_decode=2) as (router, ds, reg):
        c = RouterClient(*router.address, call_timeout=60.0)
        _throttle(ds)
        rs = np.random.RandomState(9)
        prompt, max_new = rs.randint(0, VOCAB, 9), 24
        rid = c.submit(prompt, max_new)
        toks, cursor = [], 0
        deadline = time.monotonic() + 60.0
        while not toks:
            assert time.monotonic() < deadline
            got, done, _ = c.poll(rid, cursor)
            toks.extend(got)
            cursor += len(got)
            assert not done, "stream finished before the kill window"
            time.sleep(0.01)
        rec = router._recs[rid]
        victim = next(d for i, d in enumerate(ds)
                      if f"d{i}" == rec.worker)
        ds.remove(victim)                     # teardown stops the rest
        victim.stop()                         # leave -> immediate eviction
        deadline = time.monotonic() + 20.0
        while len(router._members("decode")) != 1:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        got = _drain_interleaved(c, {"s": rid}, cursors={"s": cursor})["s"]
        full = np.concatenate([np.asarray(toks, np.int32), got])
        np.testing.assert_array_equal(full,
                                      _ref(model, params, prompt, max_new))
        assert rec.reroutes == 1
        assert _counter(reg, "router.reroutes_total", reason="left") >= 1
        c.close()


def test_router_restart_replay_no_double_execution(model_and_params):
    """Kill and restart the ROUTER mid-stream on the same port: the
    client ladder resubmits the ORIGINAL request under the ORIGINAL
    submit_key and keeps its cursor; the worker's replay cache answers
    with the original rid — the engine admits nothing new, and the
    stream's tail re-emerges exactly at the cursor."""
    from paddle_tpu.serving import RouterClient, ServingRouter
    model, params = model_and_params
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    with _fleet(model, params, n_decode=1, port=port) as (router, ds, reg):
        c = RouterClient(*router.address, retries=2, retry_delay=0.05,
                         call_timeout=30.0)
        _throttle(ds)
        rs = np.random.RandomState(31)
        prompt, max_new = rs.randint(0, VOCAB, 9), 24
        gen = c.stream(prompt, max_new, poll_interval_s=0.01,
                       max_recoveries=100)
        toks = [next(gen)]                    # at least one token landed
        admitted = ds[0].engine._next_rid
        router.stop()
        router2 = ServingRouter("127.0.0.1", port, ttl=1.0,
                                scrape_interval_s=0.1).start()
        try:
            toks.extend(gen)                  # recovery ladder drains it
            np.testing.assert_array_equal(
                np.asarray(toks, np.int32),
                _ref(model, params, prompt, max_new))
            # no double execution: the replay cache answered the
            # resubmission — the engine never admitted a second record
            assert ds[0].engine._next_rid == admitted
            # ... and the recovery really ran through router2 (the
            # original submit_key re-registered there)
            assert len(router2._recs) == 1
        finally:
            router2.stop()
        c.close()


def test_replay_prefix_len_hardening_router_and_worker(model_and_params):
    """Satellite: a router-forwarded (or transport-retried) resubmission
    may not inflate its declared prefix_len past the recorded original —
    both the router AND the worker daemon refuse with the same structured
    invalid_argument."""
    model, params = model_and_params
    with _fleet(model, params, n_decode=1) as (router, ds, reg):
        prompt = list(range(1, 10))
        mc = MasterClient(*router.address)
        req = {"op": "route_submit", "prompt": prompt, "max_new": 2,
               "submit_key": "k-route", "prefix_len": 2}
        r1 = mc._call(dict(req))
        assert r1["ok"]
        replay = mc._call(dict(req, prefix_len=8))
        assert not replay["ok"]
        assert replay["code"] == "invalid_argument"
        assert "prefix_len" in replay["error"]
        same = mc._call(dict(req))            # honest replay: original rid
        assert same["ok"] and same["rid"] == r1["rid"]
        mc.close()
        # the worker daemon enforces the same rule on srv_submit replays
        mw = MasterClient(*ds[0].address)
        wreq = {"op": "srv_submit", "prompt": prompt, "max_new": 2,
                "submit_key": "k-worker", "prefix_len": 2}
        w1 = mw._call(dict(wreq))
        assert w1["ok"]
        wre = mw._call(dict(wreq, prefix_len=8))
        assert not wre["ok"] and wre["code"] == "invalid_argument"
        wsame = mw._call(dict(wreq))
        assert wsame["ok"] and wsame["rid"] == w1["rid"]
        assert "_prefix_len" not in wsame     # internal keys never leak
        mw.close()


def test_final_connection_error_reports_attempts_and_epoch():
    """Satellite: the final ConnectionError a client surfaces carries the
    attempt count and the last membership epoch it saw — the two numbers
    that distinguish 'router down' from 'I was partitioned and my view
    is stale'."""
    from paddle_tpu.serving import RouterClient, ServingRouter
    router = ServingRouter().start()
    c = RouterClient(*router.address, retries=3, retry_delay=0.01)
    c.serving_stats()                         # records the stamped epoch
    assert c.last_epoch is not None
    router.stop()
    with pytest.raises(ConnectionError) as ei:
        c.serving_stats()
    msg = str(ei.value)
    assert re.search(r"3 attempt\(s\)", msg), msg
    assert f"last seen membership epoch {c.last_epoch}" in msg
    c.close()


def test_chaos_route_submit_raise_is_structured_and_recoverable(
        model_and_params):
    """A ``route.submit`` raise (the placement hop dying) comes back as a
    structured error on a connection that keeps working; the retry
    places cleanly and streams exactly."""
    from paddle_tpu.serving import RouterClient
    model, params = model_and_params
    with _fleet(model, params, n_decode=1) as (router, ds, reg):
        c = RouterClient(*router.address, call_timeout=60.0)
        rs = np.random.RandomState(17)
        prompt = rs.randint(0, VOCAB, 7)
        plan = faults.FaultPlan().add("route.submit", "raise", nth=1)
        with plan.installed():
            with pytest.raises((ValueError, RuntimeError)):
                c.submit(prompt, 4)
            rid = c.submit(prompt, 4)         # second hit passes clean
            got = _drain_interleaved(c, {"s": rid})["s"]
        np.testing.assert_array_equal(got, _ref(model, params, prompt, 4))
        assert c.serving_stats()["n_decode_workers"] == 1
        c.close()


def test_chaos_adopt_raise_falls_back_and_streams_exact(model_and_params):
    """A ``srv.adopt`` raise (the decode hop dying mid-adopt) must not
    lose the request: the router's prefill forward fails over to direct
    decode-side prefill (degraded but correct) and the stream still
    bit-equals solo decode."""
    from paddle_tpu.serving import RouterClient
    model, params = model_and_params
    with _fleet(model, params, n_decode=1, prefill=True) as (router, ds,
                                                             reg):
        c = RouterClient(*router.address, call_timeout=60.0)
        rs = np.random.RandomState(23)
        prompt, max_new = rs.randint(0, VOCAB, 11), 12
        plan = faults.FaultPlan().add("srv.adopt", "raise", nth=1)
        with plan.installed():
            rid = c.submit_with_backoff(prompt, max_new)
            got = _drain_interleaved(c, {"s": rid})["s"]
        np.testing.assert_array_equal(got,
                                      _ref(model, params, prompt, max_new))
        assert _counter(reg, "router.reroutes_total",
                        reason="prefill_fallback") >= 1
        c.close()


def test_slow_ship_dominates_timeline_exemplar_and_trace_cli(
        model_and_params, capsys):
    """Acceptance (ISSUE 19): force the SHIP phase slow — a ``srv.ship``
    delay fault lands inside the measured ship window — and the stitched
    timeline names ship dominant, the router store captures it as a
    slow exemplar, and ``paddle_tpu obs trace --master`` prints the same
    attribution from the live aggregator."""
    from paddle_tpu import cli
    from paddle_tpu.serving import RouterClient
    model, params = model_and_params
    with _fleet(model, params, n_decode=1, prefill=True) as (router, ds,
                                                             reg):
        c = RouterClient(*router.address, call_timeout=60.0)
        rs = np.random.RandomState(29)
        # warm both pools first so compile walls don't drown the fault
        warm = c.submit_with_backoff(rs.randint(0, VOCAB, 11), 4)
        _drain_interleaved(c, {"w": warm})
        plan = faults.FaultPlan().add("srv.ship", "delay", delay_s=0.25)
        with plan.installed():
            rid = c.submit_with_backoff(rs.randint(0, VOCAB, 11), 8)
            _drain_interleaved(c, {"s": rid})
        key = router._recs[rid].key
        store = router.server.aggregator.requests
        deadline = time.monotonic() + 15.0
        while True:
            st = store.get(key)
            if st is not None and st["done"]:
                break
            assert time.monotonic() < deadline, \
                "slow-ship timeline never stitched done"
            time.sleep(0.05)
        assert st["dominant"] == "ship"
        assert st["breakdown"]["ship"] >= 0.25
        assert st["ttft_s"] >= 0.25           # the hop is IN the TTFT
        # the completed slow request is a window exemplar naming ship
        # (the warm request may out-score it with its compile wall)
        assert any(e["key"] == key and e["dominant"] == "ship"
                   for e in store.exemplars())
        # the live-aggregator CLI surface prints the same attribution
        host, port = router.address
        assert cli.main(["obs", "trace", key,
                         "--master", f"{host}:{port}"]) == 0
        out = capsys.readouterr().out
        assert f"request {key}" in out and "dominant=ship" in out
        assert "ship=" in out
        c.close()


def test_kill9_decode_worker_midstream_streams_exact(model_and_params,
                                                     tmp_path):
    """THE chaos bar: two decode workers (the victim a REAL subprocess
    `paddle_tpu serve --router ...`), kill -9 the one holding live
    streams mid-generation -> heartbeat eviction -> re-route onto the
    survivor -> every client stream completes with exactly the
    solo-decode token sequence: zero lost, zero duplicated tokens."""
    from paddle_tpu import obs
    from paddle_tpu.serving import (RouterClient, ServingDaemon,
                                    ServingEngine, ServingRouter)
    model, params = model_and_params
    reg = obs.MetricsRegistry()
    session = obs.ObsSession(registry=reg).install()
    router = ServingRouter(ttl=1.0, scrape_interval_s=0.1).start()
    host, port = router.address
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # "a-victim" sorts before "z-survivor": with zero history both score
    # 0 and the tiebreak sends the first streams at the victim
    # --segment 1: the victim emits ONE token per dispatch, so a long
    # budget is genuinely in flight for hundreds of milliseconds — the
    # kill lands mid-stream, not in a warm-cache instant finish
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu", "serve",
         "--vocab", str(VOCAB), "--d_model", str(D), "--n_heads", str(H),
         "--n_layers", str(L), "--max_len", str(MAX_LEN), "--seed", "0",
         "--slots", "2", "--segment", "1", "--page_block", "8",
         "--cache_bucket", "32",
         "--router", f"{host}:{port}", "--worker", "a-victim"],
        stdout=subprocess.PIPE, text=True, env=env, cwd=REPO)
    survivor = None
    try:
        line = proc.stdout.readline()
        assert re.match(r"SERVING \S+ \d+", line), line
        line = proc.stdout.readline()
        assert re.match(r"JOINED \S+ epoch \d+", line), line
        survivor = ServingDaemon(ServingEngine(
            model, params, slots=2, segment=8, page_block=8,
            cache_bucket=32)).start()
        survivor.join_router(router.address, "z-survivor", role="decode")
        deadline = time.monotonic() + 30.0
        while len(router._members("decode")) != 2:
            assert time.monotonic() < deadline
            time.sleep(0.05)

        c = RouterClient(host, port, call_timeout=120.0)
        rs = np.random.RandomState(41)
        reqs = {i: (rs.randint(0, VOCAB, n), g)
                for i, (n, g) in enumerate([(9, 96), (13, 80)])}
        work = {i: c.submit(p, g) for i, (p, g) in reqs.items()}
        # poll until at least one stream is MID-flight on the victim:
        # tokens delivered, not done, placed there — that is the stream
        # the kill must not lose a token of
        cursors = {i: 0 for i in work}
        toks = {i: [] for i in work}
        done_f = {i: False for i in work}
        deadline = time.monotonic() + 120.0
        while True:
            assert time.monotonic() < deadline, "no mid-flight stream"
            for i in work:
                if done_f[i]:
                    continue
                got, done, _ = c.poll(work[i], cursors[i])
                toks[i].extend(got)
                cursors[i] += len(got)
                done_f[i] = done
            on_victim = [i for i in work
                         if not done_f[i] and toks[i]
                         and router._recs[work[i]].worker == "a-victim"]
            if on_victim:
                break
            assert not all(done_f.values()), \
                "every stream finished before the kill window"
            time.sleep(0.002)

        # the scrape pump (0.1s) must have pulled the victim's timeline
        # before the kill erases its ledger — that pull is exactly what
        # lets the stitched timeline survive a kill -9
        store = router.server.aggregator.requests
        vkey = router._recs[work[on_victim[0]]].key
        deadline = time.monotonic() + 15.0
        while True:
            stv = store.get(vkey)
            if stv is not None and any(
                    e["phase"] == "first_token"
                    and e.get("worker") == "a-victim"
                    for e in stv["events"]):
                break
            assert time.monotonic() < deadline, \
                "victim's first_token never reached the router store"
            time.sleep(0.02)

        os.kill(proc.pid, signal.SIGKILL)     # no goodbye, no leave
        deadline = time.monotonic() + 30.0
        while len(router._members("decode")) != 1:   # TTL eviction
            assert time.monotonic() < deadline, "eviction never happened"
            time.sleep(0.05)

        live = {i: work[i] for i in work if not done_f[i]}
        rest = _drain_interleaved(c, live, cursors=cursors)
        for i, (p, g) in reqs.items():
            full = np.concatenate([np.asarray(toks[i], np.int32),
                                   rest.get(i, np.zeros(0, np.int32))])
            np.testing.assert_array_equal(full, _ref(model, params, p, g))
        assert _counter(reg, "router.reroutes_total", reason="evicted") \
            >= len(on_victim)

        # satellite (ISSUE 19): the re-routed stream's STITCHED timeline
        # holds both workers' phases — the dead victim's leg 0 (pulled by
        # the scrape pump before the kill) and the survivor's derived
        # {key}#r1 leg — with exactly one canonical first_token
        deadline = time.monotonic() + 15.0
        while True:
            st = store.get(vkey)
            if st is not None and st["done"] and 1 in st["legs"]:
                break
            assert time.monotonic() < deadline, \
                "re-routed leg never stitched done on the router store"
            time.sleep(0.05)
        assert st["legs"] == [0, 1] and st["reroutes"] == 1
        # the victim's identity survives its own death; the in-process
        # survivor's leg is stamped by whichever pump pushed it last
        # (the survivor scrape or the router's own-ledger push)
        assert "a-victim" in st["workers"] and len(st["workers"]) >= 2
        fts = [e for e in st["events"] if e["phase"] == "first_token"]
        assert len(fts) == 2
        assert [bool(e.get("resumed")) for e in fts] == [False, True]
        assert [e["leg"] for e in fts] == [0, 1]
        # phases from every seam survived: router admission + re-route,
        # the victim's admission/decode, the survivor's remainder
        assert {e["phase"] for e in st["events"]} >= {
            "admitted", "route", "reroute", "queued", "first_token",
            "decode", "done"}
        # no gap, no double count: TTFT is the FIRST leg's first token
        assert st["ttft_s"] is not None
        assert 0 < st["ttft_s"] <= st["wall_s"]
        assert fts[0]["t_unix"] - st["t0_unix"] == \
            pytest.approx(st["ttft_s"])
        c.close()
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=30)
        if survivor is not None:
            survivor.stop()
        router.stop()
        session.uninstall()
