"""KV-page shipping (serving/ship.py + PagePool.export_slot/adopt_slot):
the disaggregation wire contract. Serialization round-trips are BIT-exact
for f32 and int8 (scales included), damage — flipped payload bytes, bad
chunk CRCs, truncation, a chaos-injected ``srv.ship`` corrupt — is refused
structurally (ShipError, never adopted), and the end-to-end two-pool path
(prefill pool admits -> export -> chunks -> reassemble -> decode engine
adopts) streams wire-greedy tokens equal to solo decode."""

import numpy as np
import pytest

from paddle_tpu import faults
from paddle_tpu.serving import ShipError
from paddle_tpu.serving import ship

VOCAB, D, H, L, MAX_LEN = 97, 32, 4, 2, 128


def _arrays(kv_dtype=None, seed=0):
    """A plausible slot shipment: per-layer k/v page rows (+ int8 scales)."""
    rs = np.random.RandomState(seed)
    out = {}
    for i in range(L):
        for nm in (f"k{i}", f"v{i}"):
            if kv_dtype == "int8":
                out[nm] = rs.randint(-128, 128, (3, 8, H, D // H),
                                     dtype=np.int8)
                out[f"{nm}_scale"] = rs.rand(3, 8, H).astype(np.float32)
            else:
                out[nm] = rs.randn(3, 8, H, D // H).astype(np.float32)
    return out


# -- serialization: pure, no native runtime needed --------------------------

@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_pack_unpack_round_trip_bit_exact(kv_dtype):
    arrays = _arrays(kv_dtype)
    manifest, payload = ship.pack(arrays, plen=17, first=42, page_block=8,
                                  kv_dtype=kv_dtype)
    assert manifest["version"] == ship.SHIP_VERSION
    assert manifest["plen"] == 17 and manifest["first"] == 42
    got = ship.unpack(manifest, payload)
    assert set(got) == set(arrays)
    for nm in arrays:
        assert got[nm].dtype == arrays[nm].dtype
        # bit-exact, not allclose: adoption scatters these bytes into a
        # live pool and wire-greedy parity rides on identity
        assert np.array_equal(got[nm], arrays[nm])


def test_chunk_round_trip_and_idempotent_retry():
    arrays = _arrays()
    manifest, payload = ship.pack(arrays, plen=9, first=1, page_block=8,
                                  kv_dtype=None)
    frames = list(ship.iter_chunks(payload, chunk_bytes=1024))
    assert len(frames) > 1                       # actually chunked
    asm = ship.ChunkAssembler(frames[0][1])
    for seq, _total, fr in frames:
        asm.add(seq, fr["data"], fr["crc"])
    # at-least-once transport: a retried chunk re-verifies, no corruption
    asm.add(frames[0][0], frames[0][2]["data"], frames[0][2]["crc"])
    assert asm.complete
    got = ship.unpack(manifest, asm.payload())
    for nm in arrays:
        assert np.array_equal(got[nm], arrays[nm])


def test_corrupted_payload_refused_structurally():
    arrays = _arrays()
    manifest, payload = ship.pack(arrays, plen=9, first=1, page_block=8,
                                  kv_dtype=None)
    bad = bytearray(payload)
    bad[len(bad) // 2] ^= 0x40
    with pytest.raises(ShipError, match="CRC"):
        ship.unpack(manifest, bytes(bad))
    with pytest.raises(ShipError, match="truncated|lost"):
        ship.unpack(manifest, payload[:-4])
    with pytest.raises(ShipError, match="version"):
        ship.unpack(dict(manifest, version=99), payload)
    # an entry-level lie is caught even though the payload CRC still holds
    m2 = dict(manifest, entries=[dict(manifest["entries"][0],
                                      nbytes=manifest["entries"][0]["nbytes"]
                                      - 1)])
    with pytest.raises(ShipError, match="disagrees"):
        ship.unpack(m2, payload)


def test_chunk_corruption_refused_at_arrival():
    payload = b"x" * 4096
    frames = list(ship.iter_chunks(payload, chunk_bytes=1024))
    asm = ship.ChunkAssembler(frames[0][1])
    seq, _t, fr = frames[1]
    with pytest.raises(ShipError, match="CRC"):
        asm.add(seq, fr["data"], fr["crc"] ^ 0x1)
    with pytest.raises(ShipError, match="base64"):
        asm.add(seq, "!!! not base64 !!!", fr["crc"])
    with pytest.raises(ShipError, match="outside"):
        asm.add(99, fr["data"], fr["crc"])
    with pytest.raises(ShipError, match="incomplete"):
        asm.payload()


def test_chaos_srv_ship_corrupt_caught_by_chunk_crc():
    """The ``srv.ship`` fault site filters each raw chunk AFTER its CRC
    was stamped — injected corruption is exactly wire damage, and the
    receiver refuses the chunk instead of assembling a poisoned payload."""
    payload = bytes(range(256)) * 64
    plan = faults.FaultPlan(seed=7).add("srv.ship", "corrupt", nth=2)
    with plan.installed():
        frames = list(ship.iter_chunks(payload, chunk_bytes=4096))
    asm = ship.ChunkAssembler(frames[0][1])
    refused = 0
    for seq, _t, fr in frames:
        try:
            asm.add(seq, fr["data"], fr["crc"])
        except ShipError:
            refused += 1
    assert refused == 1                      # exactly the injected hit
    assert not asm.complete                  # damage never adopted
    with pytest.raises(ShipError, match="incomplete"):
        asm.payload()


def test_chaos_srv_ship_truncate_caught():
    payload = b"\xab" * 8192
    plan = faults.FaultPlan(seed=7).add("srv.ship", "truncate", nth=1,
                                        truncate_frac=0.5)
    with plan.installed():
        frames = list(ship.iter_chunks(payload, chunk_bytes=4096))
    asm = ship.ChunkAssembler(frames[0][1])
    with pytest.raises(ShipError, match="CRC"):
        for seq, _t, fr in frames:
            asm.add(seq, fr["data"], fr["crc"])


# -- two-pool end-to-end: prefill pool -> wire -> decode engine -------------

from paddle_tpu.runtime import native_available  # noqa: E402

needs_native = pytest.mark.skipif(not native_available(),
                                  reason="native host runtime unavailable")


@pytest.fixture(scope="module")
def model_and_params():
    import jax
    from paddle_tpu.models import TransformerLM
    model = TransformerLM(VOCAB, d_model=D, n_heads=H, n_layers=L,
                          max_len=MAX_LEN)
    return model, model.init(jax.random.PRNGKey(0))


def _ship_over_wire(pool, prompt, max_new):
    """Prefill-worker half: admit into ``pool``, export the slot, push the
    payload through the real chunk framing, reassemble, unpack. Returns
    (manifest, arrays) as the decode side would see them."""
    from paddle_tpu.serving.batcher import Request
    r = Request(-1, np.asarray(prompt, np.int32), int(max_new))
    pool.validate(r)
    left = pool.effective_budget(int(r.prompt.size), int(max_new))
    plan = pool.plan_admission(r.prompt, left)
    assert pool.evict_for(plan.need_pages, 0, protect=[plan])
    first = int(pool.admit([(0, plan)])[0])
    manifest, payload = pool.export_slot(0, first)
    pool.free_slot(0)
    frames = list(ship.iter_chunks(payload, chunk_bytes=8192))
    asm = ship.ChunkAssembler(frames[0][1])
    for seq, _t, fr in frames:
        asm.add(seq, fr["data"], fr["crc"])
    return manifest, ship.unpack(manifest, asm.payload())


def _drain_engine(eng, rid, steps=4000):
    for _ in range(steps):
        eng.step()
        toks, done, reason = eng.poll(rid)
        if done:
            return np.asarray(toks, np.int32), reason
    raise AssertionError("engine never finished the adopted request")


@needs_native
def test_shipped_decode_equals_solo_decode_f32(model_and_params):
    """The acceptance bar: tokens decoded from ADOPTED pages (prefill in
    one pool, decode in another, payload through the real chunked wire
    format) bit-equal solo single-engine greedy decode."""
    import jax.numpy as jnp
    from paddle_tpu.serving import PagePool, ServingEngine
    model, params = model_and_params
    rs = np.random.RandomState(11)
    prompt, max_new = rs.randint(0, VOCAB, 13), 24
    pre = PagePool(model, params, slots=2, segment=8, page_block=8,
                   cache_bucket=32)
    manifest, arrays = _ship_over_wire(pre, prompt, max_new)
    eng = ServingEngine(model, params, slots=2, segment=8, page_block=8,
                        cache_bucket=32)
    rid = eng.submit_prefilled(manifest["plen"], manifest["first"], arrays,
                               max_new=max_new)
    got, reason = _drain_engine(eng, rid)
    assert reason == "length"
    want = np.asarray(model.generate_cached(
        params, jnp.asarray(prompt[None]), steps=max_new))[0, prompt.size:]
    np.testing.assert_array_equal(got, want)


@needs_native
def test_shipped_decode_equals_solo_decode_int8(model_and_params):
    """Same bar for quantized KV: the int8 rows AND their f32 scale planes
    ship; parity target is a solo int8-KV engine (int8 changes numerics,
    so the reference must share the dtype)."""
    from paddle_tpu.serving import PagePool, ServingEngine
    model, params = model_and_params
    rs = np.random.RandomState(12)
    prompt, max_new = rs.randint(0, VOCAB, 11), 20

    solo = ServingEngine(model, params, slots=2, segment=8, page_block=8,
                         cache_bucket=32, kv_dtype="int8")
    srid = solo.submit(np.asarray(prompt, np.int32), max_new)
    want, wreason = _drain_engine(solo, srid)

    pre = PagePool(model, params, slots=2, segment=8, page_block=8,
                   cache_bucket=32, kv_dtype="int8")
    manifest, arrays = _ship_over_wire(pre, prompt, max_new)
    assert any(nm.endswith("_scale") for nm in arrays)   # scales shipped
    assert manifest["kv_dtype"] == "int8"
    eng = ServingEngine(model, params, slots=2, segment=8, page_block=8,
                        cache_bucket=32, kv_dtype="int8")
    rid = eng.submit_prefilled(manifest["plen"], manifest["first"], arrays,
                               max_new=max_new)
    got, reason = _drain_engine(eng, rid)
    assert reason == wreason
    np.testing.assert_array_equal(got, want)


@needs_native
def test_adopt_refuses_geometry_and_name_mismatch(model_and_params):
    """A shipment whose arrays disagree with the receiving pool (missing
    planes, wrong dtype) is refused before any page is touched."""
    from paddle_tpu.serving import PagePool, ServingEngine
    model, params = model_and_params
    rs = np.random.RandomState(13)
    prompt, max_new = rs.randint(0, VOCAB, 9), 8
    pre = PagePool(model, params, slots=2, segment=8, page_block=8,
                   cache_bucket=32)
    manifest, arrays = _ship_over_wire(pre, prompt, max_new)
    eng = ServingEngine(model, params, slots=2, segment=8, page_block=8,
                        cache_bucket=32)
    missing = dict(arrays)
    missing.pop("k0")
    # refused at SUBMIT time (structured ValueError -> the daemon's
    # invalid_argument reply), never on the scheduler thread mid-adoption
    with pytest.raises(ValueError, match="disagree"):
        eng.submit_prefilled(manifest["plen"], manifest["first"], missing,
                             max_new=max_new)
    f64 = {nm: a.astype(np.float64) if not nm.endswith("_scale") else a
           for nm, a in arrays.items()}
    with pytest.raises(ValueError, match="lossy cast"):
        eng.submit_prefilled(manifest["plen"], manifest["first"], f64,
                             max_new=max_new)
