"""Trainer-driver tests: events, evaluators, checkpoints, checkgrad, test loop.

Shaped like the reference's trainer tests (SURVEY.md §4.4 test_Trainer.cpp,
test_TrainerOnePass.cpp — tiny end-to-end trainings with embedded data)."""

import io
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import data as pdata
from paddle_tpu import parallel as pp
from paddle_tpu.data import DataFeeder, DenseSlot, IndexSlot, batch
from paddle_tpu.data.dataset import mnist
from paddle_tpu.nn import Linear, Module
from paddle_tpu.optimizer import Adam, SGD
from paddle_tpu.trainer import (ClassificationErrorEvaluator, EvaluatorGroup,
                                SumEvaluator, Trainer, event, from_tar,
                                latest_pass, load_checkpoint, save_checkpoint,
                                to_tar)


class _MLP(Module):
    def __init__(self):
        super().__init__()
        self.l1 = Linear(784, 64, act=jax.nn.relu)
        self.l2 = Linear(64, 10)

    def __call__(self, params, x, **kw):
        return self.l2(params["l2"], self.l1(params["l1"], x))


def _loss(model):
    def loss(params, x, y):
        logp = jax.nn.log_softmax(model(params, x))
        return -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
    return loss


def _outputs(model):
    def outputs(params, x, y):
        return {"logits": model(params, x), "labels": y}
    return outputs


def _reader():
    return batch(mnist.train(512), 64, drop_last=True)


_feeder = DataFeeder([DenseSlot(784), IndexSlot()])


def test_train_events_and_learning():
    model = _MLP()
    trainer = Trainer(_loss(model), Adam(1e-3), outputs_fn=_outputs(model),
                      evaluators=[ClassificationErrorEvaluator(), SumEvaluator()])
    seen = []
    costs = []

    def handler(e):
        seen.append(type(e).__name__)
        if isinstance(e, event.EndIteration):
            costs.append(e.cost)
            assert e.evaluator_result is not None

    params = model.init(jax.random.PRNGKey(0))
    params, _ = trainer.train(_reader(), params, num_passes=2,
                              event_handler=handler,
                              feeder=lambda rows: _feeder.feed(rows))
    assert "BeginPass" in seen and "EndPass" in seen
    assert "BeginIteration" in seen and "EndIteration" in seen
    assert costs[-1] < costs[0]  # it learns
    # evaluator accumulated over the pass
    res = trainer.evaluators.result()
    assert 0.0 <= res["classification_error"] <= 1.0


def test_test_loop():
    model = _MLP()
    trainer = Trainer(_loss(model), SGD(0.1), outputs_fn=_outputs(model),
                      evaluators=[ClassificationErrorEvaluator()])
    params = model.init(jax.random.PRNGKey(0))
    out = trainer.test(lambda: batch(mnist.test(128), 64)(), params,
                       feeder=lambda rows: _feeder.feed(rows))
    assert out["cost"] > 0
    assert "classification_error" in out["evaluator_result"]


def test_tar_roundtrip_and_crc():
    params = {"a": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)},
              "b": [np.ones(2), np.zeros(3)]}
    buf = io.BytesIO()
    to_tar(buf, params)
    buf.seek(0)
    back = from_tar(buf)
    np.testing.assert_allclose(back["a"]["w"], params["a"]["w"])
    assert isinstance(back["b"], list)
    np.testing.assert_allclose(back["b"][1], np.zeros(3))
    # corrupt a byte -> CRC failure
    raw = bytearray(buf.getvalue())
    # flip a byte inside the first npy payload (past the 512-byte tar header)
    raw[600] ^= 0xFF
    with pytest.raises(ValueError):
        from_tar(io.BytesIO(bytes(raw)))


def test_checkpoint_save_resume(tmp_path):
    out = str(tmp_path / "ckpt")
    model = _MLP()
    trainer = Trainer(_loss(model), Adam(1e-3), output_dir=out)
    params = model.init(jax.random.PRNGKey(0))
    params, _ = trainer.train(_reader(), params, num_passes=2,
                              feeder=lambda rows: _feeder.feed(rows))
    assert latest_pass(out) == 1
    p2, s2, st = load_checkpoint(out)
    assert st["pass_id"] == 1
    # resume continues at pass 2
    trainer2 = Trainer(_loss(model), Adam(1e-3), output_dir=out)
    passes = []
    trainer2.train(_reader(), model.init(jax.random.PRNGKey(1)), num_passes=1,
                   event_handler=lambda e: passes.append(e.pass_id)
                   if isinstance(e, event.BeginPass) else None,
                   feeder=lambda rows: _feeder.feed(rows), resume=True)
    assert passes == [2]


def test_checkgrad():
    # smooth activations only — finite differences straddle relu kinks
    class Smooth(Module):
        def __init__(self):
            super().__init__()
            self.l1 = Linear(784, 32, act=jnp.tanh)
            self.l2 = Linear(32, 10)

        def __call__(self, params, x, **kw):
            return self.l2(params["l2"], self.l1(params["l1"], x))

    model = Smooth()
    trainer = Trainer(_loss(model), SGD(0.1))
    params = model.init(jax.random.PRNGKey(0))
    rows = list(batch(mnist.train(32), 32)())[0]
    b = _feeder.feed(rows)
    assert trainer.check_gradient(params, b, max_checks_per_param=3)


def test_trainer_with_mesh_dp():
    mesh = pp.make_mesh(data=8)
    model = _MLP()
    trainer = Trainer(_loss(model), SGD(0.1), mesh=mesh)
    params = model.init(jax.random.PRNGKey(0))
    costs = []
    trainer.train(_reader(), params, num_passes=1,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, event.EndIteration) else None,
                  feeder=lambda rows: _feeder.feed(rows))
    assert costs[-1] < costs[0]


def test_benchmark_job():
    model = _MLP()
    trainer = Trainer(_loss(model), SGD(0.1))
    params = model.init(jax.random.PRNGKey(0))
    r = trainer.benchmark(lambda: batch(mnist.train(128), 64, drop_last=True)(),
                          params, feeder=lambda rows: _feeder.feed(rows),
                          warmup=1, iters=3)
    assert r["ms_per_batch"] > 0


def test_tar_preserves_empty_containers_and_tuples():
    """SGD optimizer state has {} slots per param: structure (incl. empty
    containers and tuple-ness) must survive to_tar/from_tar so resume works
    (ADVICE r1 high)."""
    from paddle_tpu.optimizer import SGD
    params = {"fc": {"w": np.ones((3, 2), np.float32),
                     "b": np.zeros((2,), np.float32)}}
    opt = SGD(0.1)
    state = opt.init(params)
    buf = io.BytesIO()
    to_tar(buf, state)
    buf.seek(0)
    back = from_tar(buf)
    assert (jax.tree_util.tree_structure(back)
            == jax.tree_util.tree_structure(state))
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    opt.update(grads, back, params)  # must not KeyError
    # tuples round-trip as tuples
    tup = {"pair": (np.ones(2, np.float32), np.zeros(3, np.float32)), "empty": []}
    buf = io.BytesIO()
    to_tar(buf, tup)
    buf.seek(0)
    back = from_tar(buf)
    assert isinstance(back["pair"], tuple) and back["empty"] == []


def test_nan_guard_raises():
    """Non-finite loss must abort the pass loop — the feenableexcept
    (TrainerMain.cpp:49) analog."""
    model = _MLP()

    def bad_loss(params, x, y):
        return _loss(model)(params, x, y) / 0.0   # inf/nan every batch

    trainer = Trainer(bad_loss, SGD(0.1))
    params = model.init(jax.random.PRNGKey(0))
    with pytest.raises(FloatingPointError, match="non-finite"):
        trainer.train(_reader(), params, num_passes=1,
                      feeder=lambda rows: _feeder.feed(rows))


def test_eval_outputs_fused_into_step():
    """Evaluator outputs must come from the SAME jitted step (no second
    forward dispatch) — the round-1 double-forward fix."""
    model = _MLP()
    calls = {"n": 0}
    base_outputs = _outputs(model)

    def counting_outputs(params, x, y):
        calls["n"] += 1          # traced once per jit compile, not per batch
        return base_outputs(params, x, y)

    trainer = Trainer(_loss(model), SGD(0.1), outputs_fn=counting_outputs,
                      evaluators=[ClassificationErrorEvaluator()])
    params = model.init(jax.random.PRNGKey(0))
    trainer.train(_reader(), params, num_passes=1,
                  feeder=lambda rows: _feeder.feed(rows))
    # traced by the fused train step -> at most a couple of traces (train step
    # compile + optional standalone uses), NOT once per batch
    assert calls["n"] <= 2, f"outputs_fn traced {calls['n']} times"


# slow: profiler-smoke variant of the benchmark path (18s)
@pytest.mark.slow
def test_benchmark_with_xla_profile(tmp_path):
    """--job=time with an XLA trace (hl_profiler / test_GpuProfiler.cpp
    analog): trace artifacts must land in the log dir."""
    from paddle_tpu.utils import profiler

    model = _MLP()
    trainer = Trainer(_loss(model), SGD(0.1))
    params = model.init(jax.random.PRNGKey(0))
    d = str(tmp_path / "trace")
    res = trainer.benchmark(_reader(), params,
                            feeder=lambda rows: _feeder.feed(rows),
                            warmup=1, iters=2, profile_dir=d)
    assert res["ms_per_batch"] > 0
    files = profiler.trace_files(d)
    assert files, f"no .xplane.pb produced under {d}"


def test_device_memory_stats_and_profile(tmp_path):
    """HBM observability (allocator-counter analog): live stats dict and a
    pprof memory profile dump."""
    import jax.numpy as jnp

    from paddle_tpu.utils import profiler

    keep = jnp.ones((256, 256))          # something alive on the device
    stats = profiler.device_memory_stats()
    assert isinstance(stats, dict)       # CPU backend may report {}
    # backend pinned: remote/tunneled plugins abort on heap profiling
    p = profiler.save_device_memory_profile(str(tmp_path / "mem.pprof"),
                                            backend="cpu")
    assert os.path.exists(p) and os.path.getsize(p) > 0
    del keep


def test_param_stats_period_logs_magnitudes():
    """--show_parameter_stats_period analog (TrainerInternal.cpp:80-87):
    per-parameter absmax/absmean lines every N batches."""
    import logging

    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu import nn
    from paddle_tpu.optimizer import SGD
    from paddle_tpu.trainer.trainer import Trainer

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def __call__(self, params, x, **kw):
            return self.fc(params["fc"], x)

    model = Net()

    def loss(params, x, y):
        return jnp.mean((model(params, x) - y) ** 2)

    t = Trainer(loss, SGD(0.1), param_stats_period=2)
    rs = np.random.RandomState(0)

    def reader():
        for _ in range(4):
            yield (rs.randn(8, 4).astype(np.float32),
                   rs.randn(8, 2).astype(np.float32))

    # the package logger sets propagate=False (glog-style), so capture with
    # a handler attached directly rather than caplog
    records = []

    class Grab(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    lg = logging.getLogger("paddle_tpu.trainer.trainer")
    h = Grab(level=logging.INFO)
    lg.addHandler(h)
    try:
        t.train(reader, model.init(jax.random.PRNGKey(0)), num_passes=1)
    finally:
        lg.removeHandler(h)
    lines = [m for m in records if m.startswith("param ")]
    assert any("fc.w" in ln and "absmax" in ln for ln in lines)
    assert len(lines) >= 4          # 2 params x 2 dumps (batches 2 and 4)


def test_trainer_layout_shards_params_and_slots(tmp_path):
    """Trainer(mesh=..., layout=...): params AND Adam moments place
    sharded per the SpecLayout, training still converges, and a
    checkpoint-resume re-places onto the current mesh."""
    from jax.sharding import PartitionSpec as P

    model = _MLP()
    mesh = pp.make_mesh(data=2, fsdp=2, tp=2)
    layout = pp.SpecLayout()
    trainer = Trainer(_loss(model), Adam(1e-3), mesh=mesh, layout=layout,
                      output_dir=str(tmp_path))
    params, opt_state = trainer.train(_reader(), model.init(jax.random.PRNGKey(0)),
                                      num_passes=1, feeder=_feeder)
    w1 = params["l1"]["w"]                      # (784, 64): (fsdp, tp)
    assert w1.sharding.spec == P("fsdp", "tp")
    assert w1.addressable_shards[0].data.shape == (392, 32)
    m = opt_state["slots"]["l1"]["w"]["m"]      # Adam moment follows
    assert m.sharding.spec == P("fsdp", "tp")
    # resume: checkpoint gathered on save, re-placed sharded on restore
    trainer2 = Trainer(_loss(model), Adam(1e-3), mesh=mesh, layout=layout,
                       output_dir=str(tmp_path))
    params2, _ = trainer2.train(_reader(), model.init(jax.random.PRNGKey(1)),
                                num_passes=1, resume=True, feeder=_feeder)
    assert params2["l1"]["w"].sharding.spec == P("fsdp", "tp")
