"""TransformerLM (models/transformer.py): the flash-attention kernels'
model-level consumer — causality, reference-math equivalence, training,
tied head, and ring-attention sequence parallelism through the same blocks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models import TransformerLM

V, D, H, L, T = 50, 32, 4, 2, 16
B = 4


def _model(max_len=64, **kw):
    m = TransformerLM(V, d_model=D, n_heads=H, n_layers=L, max_len=max_len,
                      **kw)
    return m, m.init(jax.random.PRNGKey(0))


def _ref_logits(model, params, ids):
    """Dense reference attention (softmax over explicit [T, T] scores) run
    through the SAME parameters — validates the flash-kernel model path."""
    B_, T_ = ids.shape
    x = model.embed(params["embed"], ids) + params["pos_embed"][:T_]
    for i in range(len(model.blocks)):
        blk, p = model.blocks[i], params[f"blocks_{i}"]
        h = blk.ln1(p["ln1"], x)
        q, k, v = jnp.split(blk.qkv(p["qkv"], h), 3, axis=-1)
        sh = (B_, T_, blk.n_heads, blk.d_head)
        q, k, v = (a.reshape(sh) for a in (q, k, v))
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(blk.d_head)
        mask = jnp.tril(jnp.ones((T_, T_), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        o = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
        x = x + blk.proj(p["proj"], o.reshape(B_, T_, -1))
        h2 = blk.ln2(p["ln2"], x)
        x = x + blk.mlp_out(p["mlp_out"], blk.mlp_in(p["mlp_in"], h2))
    x = model.ln_f(params["ln_f"], x)
    return x @ params["embed"]["w"].T


def test_matches_dense_reference():
    model, params = _model()
    ids = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, V)
    got = model(params, ids)
    want = _ref_logits(model, params, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_causality():
    """Changing token t must not change logits at positions < t."""
    model, params = _model()
    ids = jax.random.randint(jax.random.PRNGKey(2), (1, T), 0, V)
    base = np.asarray(model(params, ids))
    ids2 = ids.at[0, T // 2].set((int(ids[0, T // 2]) + 1) % V)
    pert = np.asarray(model(params, ids2))
    np.testing.assert_allclose(pert[0, :T // 2], base[0, :T // 2],
                               rtol=1e-5, atol=1e-5)
    assert np.abs(pert[0, T // 2:] - base[0, T // 2:]).max() > 1e-6


def test_trains_next_token():
    """Fit a deterministic cyclic language: loss falls far below the
    uniform floor."""
    from paddle_tpu.optimizer import Adam

    model, params = _model()
    rs = np.random.RandomState(0)
    starts = rs.randint(0, V, (64,))
    ids = jnp.asarray((starts[:, None] + np.arange(T)[None, :]) % V,
                      jnp.int32)
    opt = Adam(3e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        loss, g = jax.value_and_grad(model.loss)(params, ids)
        params, state = opt.update(g, state, params)
        return params, state, loss

    losses = []
    for _ in range(60):
        params, state, l = step(params, state)
        losses.append(float(l))
    assert losses[-1] < 0.5 and losses[-1] < losses[0] * 0.2


def test_length_masked_loss():
    model, params = _model()
    ids = jax.random.randint(jax.random.PRNGKey(3), (B, T), 0, V)
    lengths = jnp.array([T, T // 2, 3, T], jnp.int32)
    lm = float(model.loss(params, ids, lengths))
    # corrupting tokens past each length must not change the masked loss
    ids2 = ids.at[1, T // 2:].set(0).at[2, 3:].set(0)
    lm2 = float(model.loss(params, ids2, lengths))
    np.testing.assert_allclose(lm, lm2, rtol=1e-6)


# slow: untied-head generate variant; tied-head generate + dense-reference
# equivalence keep the decode path covered in tier-1
@pytest.mark.slow
def test_untied_head_shape_and_generate():
    model, params = _model(tie_head=False)
    ids = jax.random.randint(jax.random.PRNGKey(4), (2, 5), 0, V)
    out = model.generate_greedy(params, ids, steps=3)
    assert out.shape == (2, 8)
    assert (np.asarray(out[:, :5]) == np.asarray(ids)).all()


def test_seq_parallel_matches_single_device():
    """The SAME blocks under causal ring attention over a seq mesh axis
    reproduce the single-device forward exactly (contiguous layout; each
    shard feeds its true global positions)."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu import parallel as pp

    n = 8
    if len(jax.devices()) < n:
        pytest.skip("needs 8 virtual devices")
    T_long = 32
    model, params = _model(max_len=T_long)
    ids = jax.random.randint(jax.random.PRNGKey(5), (2, T_long), 0, V)
    positions = jnp.broadcast_to(jnp.arange(T_long), (2, T_long))
    want = np.asarray(model(params, ids))

    mesh = pp.make_mesh(seq=n)

    def fwd(params, ids, positions):
        return model(params, ids, positions=positions, seq_axis="seq")

    sharded = jax.jit(pp.shard_map(
        fwd, mesh=mesh,
        in_specs=(P(), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"), check_vma=False))
    got = np.asarray(sharded(params, ids, positions))
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


# slow: remat-vs-no-remat equivalence is stable niche coverage (56s)
@pytest.mark.slow
def test_remat_matches_no_remat():
    """remat=True (jax.checkpoint per block) must not change values or
    gradients — only the backward's memory/FLOP trade."""
    m1, params = _model()
    m2 = TransformerLM(V, d_model=D, n_heads=H, n_layers=L, max_len=64,
                       remat=True)
    ids = jax.random.randint(jax.random.PRNGKey(6), (B, T), 0, V)
    l1, g1 = jax.value_and_grad(m1.loss)(params, ids)
    l2, g2 = jax.value_and_grad(m2.loss)(params, ids)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    flat1 = jax.tree_util.tree_leaves(g1)
    flat2 = jax.tree_util.tree_leaves(g2)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_tensor_parallel_via_sharding_rules():
    """Megatron-style TP on the transformer with ZERO model changes: qkv/
    mlp_in column-parallel, proj/mlp_out row-parallel over a `model` mesh
    axis via ShardingRules; the SPMD partitioner inserts the collectives.
    One jitted dp x tp train step matches the unsharded step exactly."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu import parallel as pp
    from paddle_tpu.optimizer import SGD

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = pp.make_mesh(data=2, model=4)
    model, params = _model()
    ids = jax.random.randint(jax.random.PRNGKey(7), (B, T), 0, V)
    opt = SGD(0.1)

    def step(params, state, ids):
        loss, g = jax.value_and_grad(model.loss)(params, ids)
        params, state = opt.update(g, state, params)
        return params, state, loss

    # unsharded reference
    p_ref, s_ref, l_ref = jax.jit(step)(params, opt.init(params), ids)

    rules = pp.ShardingRules([
        (r".*blocks_\d+/qkv/w$", P(None, "model")),
        (r".*blocks_\d+/mlp_in/w$", P(None, "model")),
        (r".*blocks_\d+/proj/w$", P("model", None)),
        (r".*blocks_\d+/mlp_out/w$", P("model", None)),
        (r".*", P()),
    ])
    sp = rules.apply(mesh, params)
    ss = jax.device_put(opt.init(sp), NamedSharding(mesh, P()))
    ids_sh = jax.device_put(ids, NamedSharding(mesh, P("data", None)))
    with mesh:
        p_tp, s_tp, l_tp = jax.jit(step)(sp, ss, ids_sh)
    np.testing.assert_allclose(float(l_tp), float(l_ref), rtol=1e-5)
    for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(jax.device_get(p_tp)),
                   key=str),
            sorted(jax.tree_util.tree_leaves_with_path(jax.device_get(p_ref)),
                   key=str)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5, err_msg=str(ka))


def test_seq_parallel_shifted_loss_matches_unsharded():
    """The seq-parallel training objective: globally-shifted inputs/targets
    sharded over the seq axis through shifted_loss == the unsharded loss
    exactly; loss(seq_axis=...) is refused (per-shard shifting is wrong)."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu import parallel as pp

    n = 8
    if len(jax.devices()) < n:
        pytest.skip("needs 8 virtual devices")
    T_long = 33                     # odd so the shifted length is 32 = 8*4
    model, params = _model(max_len=T_long)
    ids = jax.random.randint(jax.random.PRNGKey(8), (2, T_long), 0, V)
    want = float(model.loss(params, ids))

    ids_in, targets = ids[:, :-1], ids[:, 1:]
    positions = jnp.broadcast_to(jnp.arange(T_long - 1), ids_in.shape)
    mesh = pp.make_mesh(seq=n)

    def f(params, ids_in, targets, positions):
        return model.shifted_loss(params, ids_in, targets,
                                  positions=positions, seq_axis="seq")

    sharded = jax.jit(pp.shard_map(
        f, mesh=mesh,
        in_specs=(P(), P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(), check_vma=False))
    got = float(sharded(params, ids_in, targets, positions))
    np.testing.assert_allclose(got, want, rtol=2e-5)

    with pytest.raises(ValueError, match="shift"):
        model.loss(params, ids, seq_axis="seq")


# slow: full-reforward equivalence (77s); the bucketed cached-decode test and
# the serving exact-parity suite keep cached decode covered in tier-1
@pytest.mark.slow
def test_cached_decode_matches_full_reforward():
    """KV-cache incremental decode (the serving path) must match the full
    re-forward greedy token-for-token, tied and untied heads."""
    for tie in (True, False):
        model, params = _model(max_len=32, tie_head=tie)
        prompt = jax.random.randint(jax.random.PRNGKey(9), (3, 5), 0, V)
        want = np.asarray(model.generate_greedy(params, prompt, steps=12))
        got = np.asarray(model.generate_cached(params, prompt, steps=12))
        np.testing.assert_array_equal(got, want)


def test_bucketed_cached_decode_matches_unbucketed():
    """Bucketed cache reads (the serving HBM saving) must produce the
    identical token stream, including across bucket boundaries and with the
    overflow guard intact."""
    import pytest

    model, params = _model(max_len=32)
    prompt = jax.random.randint(jax.random.PRNGKey(11), (2, 5), 0, V)
    want = np.asarray(model.generate_cached(params, prompt, steps=20))
    for bucket in (8, 16, 32):   # 5+20=25 crosses several 8-boundaries
        got = np.asarray(model.generate_cached(params, prompt, steps=20,
                                               bucket=bucket))
        np.testing.assert_array_equal(got, want)
    with pytest.raises(ValueError, match="max_len"):
        model.generate_cached(params, prompt, steps=30, bucket=8)


def test_prefill_logits_match_forward():
    model, params = _model(max_len=32)
    prompt = jax.random.randint(jax.random.PRNGKey(10), (2, 7), 0, V)
    _, last = model.prefill(params, prompt)
    full = model(params, prompt)
    np.testing.assert_allclose(np.asarray(last), np.asarray(full[:, -1]),
                               rtol=1e-5, atol=1e-5)
