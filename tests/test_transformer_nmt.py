"""TransformerSeq2Seq (models/transformer_nmt.py): the flash-attention NMT
configuration — dense-reference equivalence including per-sample source
masking, decoder causality, training, and generation."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import SeqBatch
from paddle_tpu.models import TransformerSeq2Seq
import pytest

SV, TV, D, H, S, T = 40, 45, 32, 2, 10, 8
B = 3


def _model():
    m = TransformerSeq2Seq(SV, TV, d_model=D, n_heads=H, n_enc=2, n_dec=2,
                           max_len=32)
    return m, m.init(jax.random.PRNGKey(0))


def _batch(seed=0):
    rs = np.random.RandomState(seed)
    src = SeqBatch(jnp.asarray(rs.randint(0, SV, (B, S))),
                   jnp.asarray([S, 6, 3]))
    tin = SeqBatch(jnp.asarray(rs.randint(0, TV, (B, T))),
                   jnp.asarray([T, 5, 4]))
    tout = SeqBatch(jnp.asarray(rs.randint(0, TV, (B, T))), tin.lengths)
    return src, tin, tout


def _dense_attn(q, k, v, *, causal=False, kv_lens=None):
    d = q.shape[-1]
    s = jnp.einsum("bthd,bshd->bhts", q, k) / np.sqrt(d)
    if kv_lens is not None:
        ok = (jnp.arange(k.shape[1])[None, :]
              < kv_lens[:, None])[:, None, None, :]
        s = jnp.where(ok, s, -1e30)
    if causal:
        m = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(m[None, None], s, -1e30)
    return jnp.einsum("bhts,bshd->bthd", jax.nn.softmax(s, -1), v)


def _ref_logits(model, params, src, tin):
    """Same params through dense attention with explicit masks."""
    x = model.src_embed(params["src_embed"], src.data)
    x = x + params["src_pos"][:S]
    for i in range(len(model.enc_blocks)):
        blk, p = model.enc_blocks[i], params[f"enc_blocks_{i}"]
        q, k, v = (a.reshape(B, S, H, D // H) for a in jnp.split(
            blk.qkv(p["qkv"], blk.ln1(p["ln1"], x)), 3, axis=-1))
        o = _dense_attn(q, k, v, kv_lens=src.lengths)
        x = x + blk.proj(p["proj"], o.reshape(B, S, D))
        h2 = blk.ln2(p["ln2"], x)
        x = x + blk.mlp_out(p["mlp_out"], blk.mlp_in(p["mlp_in"], h2))
    memory = model.ln_enc(params["ln_enc"], x)

    y = model.trg_embed(params["trg_embed"], tin.data)
    y = y + params["trg_pos"][:T]
    for i in range(len(model.dec_blocks)):
        blk, p = model.dec_blocks[i], params[f"dec_blocks_{i}"]
        q, k, v = (a.reshape(B, T, H, D // H) for a in jnp.split(
            blk.qkv(p["qkv"], blk.ln1(p["ln1"], y)), 3, axis=-1))
        y = y + blk.self_proj(p["self_proj"], _dense_attn(
            q, k, v, causal=True).reshape(B, T, D))
        qx = blk.q_x(p["q_x"], blk.ln_x(p["ln_x"], y)).reshape(
            B, T, H, D // H)
        kx, vx = (a.reshape(B, S, H, D // H) for a in jnp.split(
            blk.kv_x(p["kv_x"], memory), 2, axis=-1))
        y = y + blk.x_proj(p["x_proj"], _dense_attn(
            qx, kx, vx, kv_lens=src.lengths).reshape(B, T, D))
        h2 = blk.ln2(p["ln2"], y)
        y = y + blk.mlp_out(p["mlp_out"], blk.mlp_in(p["mlp_in"], h2))
    y = model.ln_f(params["ln_f"], y)
    return y @ params["trg_embed"]["w"].T


def test_matches_dense_reference_with_source_masking():
    model, params = _model()
    src, tin, _ = _batch()
    got = model(params, src, tin)
    want = _ref_logits(model, params, src, tin)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_source_padding_is_invisible():
    """Changing tokens past a sample's source length must not change its
    logits at all (the kernel-level kv_lens masking)."""
    model, params = _model()
    src, tin, _ = _batch()
    out1 = model(params, src, tin)
    data2 = src.data.at[1, 6:].set(7).at[2, 3:].set(11)
    out2 = model(params, SeqBatch(data2, src.lengths), tin)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                               rtol=1e-6, atol=1e-6)


def test_decoder_causality():
    """Target token t must not influence logits at positions < t."""
    model, params = _model()
    src, tin, _ = _batch()
    out1 = model(params, src, tin)
    data2 = tin.data.at[:, -1].set((tin.data[:, -1] + 1) % TV)
    out2 = model(params, src, SeqBatch(data2, tin.lengths))
    np.testing.assert_allclose(np.asarray(out1[:, :-1]),
                               np.asarray(out2[:, :-1]),
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(np.asarray(out1[:, -1]), np.asarray(out2[:, -1]))


def test_trains():
    model, params = _model()
    src, tin, tout = _batch()

    @jax.jit
    def step(params):
        l, g = jax.value_and_grad(model.loss)(params, src, tin, tout)
        return l, jax.tree_util.tree_map(lambda p, gg: p - 0.1 * gg,
                                         params, g)

    l0, params = step(params)
    for _ in range(8):
        l, params = step(params)
    assert float(l) < float(l0)


# slow: NMT greedy-generate smoke (35s); dense-reference + masking equivalence
# keep the NMT forward covered in tier-1
@pytest.mark.slow
def test_greedy_generate_shapes_and_eos():
    model, params = _model()
    src, _, _ = _batch()
    ids = model.greedy_generate(params, src, max_len=6, eos_id=1)
    assert ids.shape == (B, 6)
    assert int(ids.max()) < TV
