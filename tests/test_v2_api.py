"""v2 facade end-to-end tests — the quick_start / fit_a_line demos driven
through the paddle.v2-style API (SURVEY.md §2.4 python/paddle/v2)."""

import io

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.v2 as paddle
from paddle_tpu.data.dataset import imdb, uci_housing
from paddle_tpu.trainer import event


@pytest.fixture(autouse=True)
def fresh_programs():
    fluid.reset_default_programs()
    yield


def test_fit_a_line_v2():
    x = paddle.layer.data("x", paddle.data_type.dense_vector(13))
    y = paddle.layer.data("y", paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(x, 1)
    cost = paddle.layer.square_error_cost(pred, y)

    trainer = paddle.SGD(cost, paddle.optimizer.SGD(0.01))
    costs = []
    trainer.train(paddle.batch(uci_housing.train(256), 64),
                  num_passes=8,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, event.EndIteration) else None,
                  feeding=[x, y])
    assert costs[-1] < costs[0] * 0.5

    # inference on test rows
    rows = list(uci_housing.test(8)())
    out = paddle.infer(pred, trainer, rows, feeding=[x, y])
    assert out.shape == (8, 1)

    # parameters facade: names, get/set, tar roundtrip
    params = trainer.parameters
    assert len(params.names()) >= 2
    buf = io.BytesIO()
    params.to_tar(buf)
    name = params.names()[0]
    orig = params.get(name)
    params.set(name, np.zeros_like(orig))
    buf.seek(0)
    params.from_tar(buf)
    np.testing.assert_allclose(params.get(name), orig)


def test_quickstart_lstm_text_classification():
    """quick_start trainer_config.lstm.py analog over the v2 facade."""
    words = paddle.layer.data("words",
                              paddle.data_type.integer_value_sequence(imdb.VOCAB))
    label = paddle.layer.data("label", paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(words, 32)
    lstm = paddle.networks.simple_lstm(emb, 32)
    pooled = paddle.layer.pooling(lstm, "max")
    logits = paddle.layer.fc(pooled, 2)
    cost = paddle.layer.classification_cost(logits, label)

    trainer = paddle.SGD(cost, paddle.optimizer.Adam(1e-2))
    costs = []
    trainer.train(paddle.batch(imdb.train(256), 32), num_passes=3,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, event.EndIteration) else None,
                  feeding=[words, label])
    assert costs[-1] < costs[0] * 0.7
    tr = trainer.test(paddle.batch(imdb.test(64), 32), feeding=[words, label])
    assert tr.cost > 0


def test_bidirectional_lstm_and_text_conv():
    words = paddle.layer.data("w",
                              paddle.data_type.integer_value_sequence(imdb.VOCAB))
    label = paddle.layer.data("y", paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(words, 16)
    bi = paddle.networks.bidirectional_lstm(emb, 16)
    conv = paddle.networks.text_conv_pool(emb, 16)
    h = paddle.layer.concat([bi, conv])
    logits = paddle.layer.fc(h, 2)
    cost = paddle.layer.classification_cost(logits, label)
    trainer = paddle.SGD(cost, paddle.optimizer.Adam(1e-2))
    costs = []
    trainer.train(paddle.batch(imdb.train(128), 32), num_passes=2,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, event.EndIteration) else None,
                  feeding=[words, label])
    assert costs[-1] < costs[0]


def test_sparse_input_trains_end_to_end():
    """quick_start LR config analog: sparse_binary_vector input -> fc ->
    classification — the round-1 dead __vals__ path now carries real data
    (sparse fc = weighted-row-sum, the SelectedRows/sparse-remote analog)."""
    DIM = 100
    rs = np.random.RandomState(0)

    def make(n, seed):
        r = np.random.RandomState(seed)
        rows = []
        for _ in range(n):
            label = int(r.randint(0, 2))
            # class-indicative feature ids: even ids -> class 0, odd -> 1
            base = r.choice(np.arange(label, DIM, 2), size=6, replace=False)
            noise = r.choice(DIM, size=2, replace=False)
            rows.append((list(np.concatenate([base, noise])), label))
        return rows

    x = paddle.layer.data("x", paddle.data_type.sparse_binary_vector(DIM))
    y = paddle.layer.data("y", paddle.data_type.integer_value(2))
    logits = paddle.layer.fc(x, 2)
    cost = paddle.layer.classification_cost(logits, y)

    trainer = paddle.SGD(cost, paddle.optimizer.Adam(5e-2))
    costs = []

    def reader():
        rows = make(256, 1)
        for i in range(0, 256, 32):
            yield rows[i:i + 32]

    trainer.train(reader, num_passes=6,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, event.EndIteration) else None,
                  feeding=[x, y])
    assert costs[-1] < costs[0] * 0.5

    # sparse_float_vector through embedding(): weighted bag-of-features
    import paddle_tpu.fluid as F
    F.reset_default_programs()
    xf = paddle.layer.data("xf", paddle.data_type.sparse_float_vector(DIM))
    emb = paddle.layer.embedding(xf, 8)
    assert emb.var.shape[-1] == 8


def test_v2_param_stats_flag(monkeypatch):
    """PDTPU-flagged per-parameter stats dump through the v2 trainer
    (--show_parameter_stats_period, TrainerInternal.cpp:80-87)."""
    import logging

    import numpy as np

    import paddle_tpu.v2 as paddle
    from paddle_tpu.data.dataset import uci_housing
    from paddle_tpu.utils.flags import FLAGS

    monkeypatch.setattr(FLAGS, "show_parameter_stats_period", 2)
    x = paddle.layer.data("x", paddle.data_type.dense_vector(13))
    y = paddle.layer.data("y", paddle.data_type.dense_vector(1))
    cost = paddle.layer.square_error_cost(paddle.layer.fc(x, 1), y)
    t = paddle.SGD(cost, paddle.optimizer.SGD(0.01))

    records = []

    class Grab(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    lg = logging.getLogger("paddle_tpu.v2.trainer")
    h = Grab(level=logging.INFO)
    lg.addHandler(h)
    try:
        t.train(paddle.batch(uci_housing.train(64), 16), num_passes=1,
                feeding=[x, y])
    finally:
        lg.removeHandler(h)
    lines = [m for m in records if m.startswith("param ")]
    assert lines and any("absmax" in ln for ln in lines)
