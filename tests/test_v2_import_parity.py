"""Verbatim v2 DSL name parity.

The reference exports 115 names from its layer DSL
(trainer_config_helpers/layers.py:34-140 ``__all__``). Every one must be
importable from ``paddle_tpu.v2.layer`` under its reference spelling —
either as the canonical implementation or a documented alias
(docs/v2_layer_parity.md).
"""
import numpy as np
import pytest

import paddle_tpu.v2.layer as L

# the reference's __all__, verbatim (layers.py:34-140)
REFERENCE_ALL = [
    "full_matrix_projection", "AggregateLevel", "ExpandLevel",
    "identity_projection", "dotmul_projection", "dotmul_operator",
    "repeat_layer", "seq_reshape_layer", "table_projection", "mixed_layer",
    "data_layer", "embedding_layer", "fc_layer", "grumemory",
    "pooling_layer", "lstmemory", "last_seq", "first_seq", "cos_sim",
    "hsigmoid", "conv_projection", "square_error_cost", "regression_cost",
    "classification_cost", "LayerOutput", "img_conv_layer",
    "img_pool_layer", "batch_norm_layer", "img_cmrnorm_layer",
    "addto_layer", "concat_layer", "seq_concat_layer", "lstm_step_layer",
    "recurrent_group", "memory", "StaticInput", "expand_layer",
    "scaling_layer", "scaling_projection", "power_layer",
    "interpolation_layer", "bilinear_interp_layer", "trans_layer",
    "rotate_layer", "sum_to_one_norm_layer", "row_l2_norm_layer",
    "get_output_layer", "LayerType", "context_projection", "beam_search",
    "maxid_layer", "GeneratedInput", "SubsequenceInput", "gru_step_layer",
    "gru_step_naive_layer", "recurrent_layer", "BaseGeneratedInput",
    "conv_operator", "conv_shift_layer", "tensor_layer",
    "selective_fc_layer", "sampling_id_layer", "slope_intercept_layer",
    "trans_full_matrix_projection", "linear_comb_layer",
    "convex_comb_layer", "ctc_layer", "warp_ctc_layer", "crf_layer",
    "crf_decoding_layer", "nce_layer", "cross_entropy_with_selfnorm",
    "cross_entropy", "BeamInput", "cross_entropy_over_beam",
    "multi_binary_label_cross_entropy", "sum_cost", "rank_cost",
    "lambda_cost", "huber_regression_cost", "huber_classification_cost",
    "block_expand_layer", "maxout_layer", "dot_prod_layer",
    "out_prod_layer", "printer_layer", "print_layer", "priorbox_layer",
    "cross_channel_norm_layer", "multibox_loss_layer",
    "detection_output_layer", "roi_pool_layer", "spp_layer", "pad_layer",
    "eos_layer", "smooth_l1_cost", "layer_support", "multiplex_layer",
    "row_conv_layer", "dropout_layer", "prelu_layer",
    "switch_order_layer", "gated_unit_layer", "crop_layer",
    "sub_nested_seq_layer", "clip_layer", "slice_projection",
    "seq_slice_layer", "kmax_seq_score_layer", "img_pool3d_layer",
    "scale_shift_layer", "img_conv3d_layer", "resize_layer",
    "sub_seq_layer", "scale_sub_region_layer",
]


def test_reference_all_is_115_names():
    assert len(REFERENCE_ALL) == 115
    assert len(set(REFERENCE_ALL)) == 115


@pytest.mark.parametrize("name", REFERENCE_ALL)
def test_reference_name_importable(name):
    """`from paddle_tpu.v2.layer import <name>` works for every reference
    spelling and yields a callable or a DSL class/enum, never None."""
    assert hasattr(L, name), name
    assert getattr(L, name) is not None


def test_enum_values_match_reference():
    assert L.AggregateLevel.TO_NO_SEQUENCE == "non-seq"
    assert L.AggregateLevel.TO_SEQUENCE == "seq"
    assert L.AggregateLevel.EACH_TIMESTEP == "non-seq"
    assert L.ExpandLevel.FROM_NO_SEQUENCE == "non-seq"
    assert L.ExpandLevel.FROM_TIMESTEP == "non-seq"
    assert L.LayerType.is_layer_type("fc")
    assert not L.LayerType.is_layer_type("no_such_layer")


def test_generated_input_is_base_generated_input():
    gi = L.GeneratedInput(size=7, embedding_size=4)
    assert isinstance(gi, L.BaseGeneratedInput)
    assert gi.bos_id is None and gi.eos_id is None


def test_recurrent_layer_runs_and_matches_manual_scan():
    """recurrent_layer compiles to a masked scan with the reference's
    h_t = act(x_t + h_{t-1} @ U + b) semantics (RecurrentLayer.cpp)."""
    import paddle_tpu.fluid as fluid
    import paddle_tpu.v2 as paddle
    from paddle_tpu.fluid.executor import Executor

    fluid.reset_default_programs()
    x = L.data("x", paddle.data_type.dense_vector_sequence(5))
    out = L.recurrent_layer(x)
    xs = np.random.RandomState(0).randn(2, 4, 5).astype(np.float32)
    lens = np.array([4, 2], np.int32)
    exe = Executor()
    exe.run(fluid.default_startup_program())
    res = np.asarray(exe.run(fluid.default_main_program(),
                             feed={"x": xs, "x__len__": lens},
                             fetch_list=[out.var.name])[0])
    assert res.shape == (2, 4, 5)
    # manual replay with the created parameters (uniquified names: find by
    # prefix in the program's parameter list)
    gb = fluid.default_main_program().global_block()
    u = np.asarray(exe.scope.get(next(n for n in gb.vars if "rnn_u" in n)))
    bvec = np.asarray(exe.scope.get(next(n for n in gb.vars
                                         if "rnn_b" in n)))
    h = np.zeros((2, 5), np.float32)
    want = np.zeros_like(xs)
    for t in range(4):
        h_new = np.tanh(xs[:, t] + h @ u + bvec)
        m = (t < lens)[:, None]
        h = np.where(m, h_new, h)
        want[:, t] = np.where(m, h, 0.0)
    np.testing.assert_allclose(res, want, rtol=1e-5, atol=1e-5)
