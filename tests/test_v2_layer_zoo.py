"""Gen-1 layer-zoo breadth: every new *_layer / *_cost in the v2 DSL builds
a program and executes through the fluid Executor — the parametrized analog
of trainer_config_helpers' per-layer configs + test_LayerGrad coverage
(SURVEY.md §2.4: layers.py 106 *_layer functions; CostLayer.cpp zoo)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.v2 as paddle
from paddle_tpu.fluid.executor import Executor

L = paddle.layer
DT = paddle.data_type

B, T, D, V = 4, 6, 8, 12


@pytest.fixture(autouse=True)
def fresh_programs():
    fluid.reset_default_programs()
    yield


def _run(out_layer, feeds):
    exe = Executor()
    exe.run(fluid.default_startup_program())
    res = exe.run(fluid.default_main_program(), feed=feeds,
                  fetch_list=[out_layer.var.name])
    return np.asarray(res[0])


def _dense(name, dim=D):
    return L.data(name, DT.dense_vector(dim))


def _seq(name, dim=D):
    return L.data(name, DT.dense_vector_sequence(dim))


RS = np.random.RandomState(0)
X = RS.randn(B, D).astype(np.float32)
X2 = RS.randn(B, D).astype(np.float32)
SEQ = RS.randn(B, T, D).astype(np.float32)
LENS = np.array([6, 4, 3, 2], np.int32)


# ----------------------------------------------------------- mixed/proj ------

def test_mixed_layer_full_matrix_plus_identity():
    x = _dense("x")
    out = L.mixed_layer(size=D, input=[
        L.full_matrix_projection(x, D),
        L.identity_projection(x),
        L.dotmul_projection(x),
    ], act="tanh", bias_attr=True)
    v = _run(out, {"x": X})
    assert v.shape == (B, D) and np.isfinite(v).all()


def test_mixed_layer_table_and_trans():
    ids = L.data("ids", DT.integer_value(V))
    x = _dense("x")
    out = L.mixed_layer(size=5, input=[
        L.table_projection(ids, 5),
        L.trans_full_matrix_projection(x, 5),
        L.scaling_projection(x) if D == 5 else L.full_matrix_projection(x, 5),
    ])
    v = _run(out, {"ids": RS.randint(0, V, B).astype(np.int32), "x": X})
    assert v.shape == (B, 5)


def test_context_projection_in_mixed():
    s = _seq("s")
    out = L.mixed_layer(size=3 * D, input=[
        L.context_projection_layer(s, context_len=3)])
    v = _run(out, {"s": SEQ, "s__len__": LENS})
    assert v.shape == (B, T, 3 * D)


def test_dotmul_operator():
    a, b = _dense("a"), _dense("b")
    out = L.mixed_layer(size=D, input=[L.dotmul_operator(a, b, scale=2.0)])
    v = _run(out, {"a": X, "b": X2})
    np.testing.assert_allclose(v, 2.0 * X * X2, rtol=1e-5)


def test_identity_projection_offset_slice():
    x = _dense("x")
    out = L.mixed_layer(size=3, input=[
        L.identity_projection(x, offset=2, size=3)])
    v = _run(out, {"x": X})
    np.testing.assert_allclose(v, X[:, 2:5], rtol=1e-6)


# ------------------------------------------------------------- misc ----------

def test_addto_cos_power_scaling_slope():
    a, b = _dense("a"), _dense("b")
    added = L.addto_layer([a, b], act="relu")
    w = L.data("w", DT.dense_vector(1))
    scaled = L.scaling_layer(added, w)
    sloped = L.slope_intercept_layer(scaled, slope=2.0, intercept=1.0)
    v = _run(sloped, {"a": X, "b": X2, "w": np.ones((B, 1), np.float32)})
    np.testing.assert_allclose(v, 2.0 * np.maximum(X + X2, 0) + 1.0,
                               rtol=1e-5)
    fluid.reset_default_programs()
    a, b = _dense("a"), _dense("b")
    cs = L.cos_sim(a, b)
    v = _run(cs, {"a": X, "b": X2})
    assert v.shape == (B,) and np.all(np.abs(v) <= 1.0 + 1e-5)
    fluid.reset_default_programs()
    x = _dense("x")
    p = L.power_layer(x)
    v = _run(p, {"x": np.abs(X) + 0.1})
    assert np.isfinite(v).all()


def test_norm_interp_comb_layers():
    x = _dense("x")
    n = L.sum_to_one_norm_layer(x)
    v = _run(n, {"x": np.abs(X) + 0.1})
    np.testing.assert_allclose(v.sum(-1), 1.0, rtol=1e-5)
    fluid.reset_default_programs()
    a, b, w = _dense("a"), _dense("b"), L.data("w", DT.dense_vector(1))
    out = L.interpolation_layer([a, b], w)
    v = _run(out, {"a": X, "b": X2, "w": np.full((B, 1), 0.3, np.float32)})
    np.testing.assert_allclose(v, 0.3 * X + 0.7 * X2, rtol=1e-5)
    fluid.reset_default_programs()
    vecs = L.data("vecs", DT.dense_vector(3 * D))
    ws = L.data("ws", DT.dense_vector(3))
    out = L.linear_comb_layer(ws, vecs, D)
    v = _run(out, {"vecs": RS.randn(B, 3 * D).astype(np.float32),
                   "ws": RS.randn(B, 3).astype(np.float32)})
    assert v.shape == (B, D)


def test_shape_layers():
    x = _dense("x")
    r = L.repeat_layer(x, 3)
    v = _run(r, {"x": X})
    assert v.shape == (B, 3 * D)
    fluid.reset_default_programs()
    s = _seq("s")
    rs = L.seq_reshape_layer(s, D // 2)
    v = _run(rs, {"s": SEQ, "s__len__": LENS})
    assert v.shape == (B, 2 * T, D // 2)
    fluid.reset_default_programs()
    x = _dense("x")
    c = L.clip_layer(x, -0.5, 0.5)
    v = _run(c, {"x": X})
    assert v.min() >= -0.5 and v.max() <= 0.5
    fluid.reset_default_programs()
    x = _dense("x")
    pd = L.pad_layer(x, [(0, 0), (1, 2)])
    v = _run(pd, {"x": X})
    assert v.shape == (B, D + 3)


def test_expand_and_maxid_sampling():
    per_seq = _dense("p")
    s = _seq("s")
    ex = L.expand_layer(per_seq, s)
    v = _run(ex, {"p": X, "s": SEQ, "s__len__": LENS})
    assert v.shape == (B, T, D)
    np.testing.assert_allclose(v[:, 0], X, rtol=1e-6)
    fluid.reset_default_programs()
    x = _dense("x")
    mid = L.max_id_layer(x)
    v = _run(mid, {"x": X})
    np.testing.assert_array_equal(v, X.argmax(-1))
    fluid.reset_default_programs()
    probs = _dense("pr")
    sid = L.sampling_id_layer(probs, seed=1)
    v = _run(sid, {"pr": np.abs(X) + 0.01})
    assert v.shape == (B,) and (0 <= v).all() and (v < D).all()


def test_multiplex_tensor_convshift():
    idx = L.data("i", DT.integer_value(2))
    a, b = _dense("a"), _dense("b")
    out = L.multiplex_layer(idx, [a, b])
    ids = np.array([0, 1, 1, 0], np.int32)
    v = _run(out, {"i": ids, "a": X, "b": X2})
    want = np.where(ids[:, None] == 0, X, X2)
    np.testing.assert_allclose(v, want, rtol=1e-6)
    fluid.reset_default_programs()
    a, b = _dense("a"), _dense("b")
    t = L.tensor_layer(a, b, size=4, act="tanh")
    v = _run(t, {"a": X, "b": X2})
    assert v.shape == (B, 4)
    fluid.reset_default_programs()
    a = _dense("a")
    k = L.data("k", DT.dense_vector(3))
    cs = L.conv_shift_layer(a, k)
    v = _run(cs, {"a": X, "k": RS.randn(B, 3).astype(np.float32)})
    assert v.shape == (B, D)


def test_image_layers():
    img = L.data("img", DT.dense_vector(8 * 8 * 3))
    # v2 images feed flat; reshape through the fluid var
    from paddle_tpu.fluid import layers as FL
    reshaped = L.LayerOutput(FL.reshape(img.var, (-1, 8, 8, 3)))
    mo = L.maxout_layer(_as4(reshaped, (B, 8, 8, 3)), groups=3)
    v = _run(mo, {"img": RS.randn(B, 8 * 8 * 3).astype(np.float32)})
    assert v.shape == (B, 8, 8, 1)


def _as4(lo, shape):
    lo.var.shape = shape  # annotate for the DSL's static-shape math
    return lo


def test_image_pipeline_layers():
    from paddle_tpu.fluid import layers as FL
    img = L.data("img", DT.dense_vector(8 * 8 * 3))
    x = _as4(L.LayerOutput(FL.reshape(img.var, (-1, 8, 8, 3))), (B, 8, 8, 3))
    feeds = {"img": RS.randn(B, 8 * 8 * 3).astype(np.float32)}

    v = _run(L.img_cmrnorm_layer(x, size=3), feeds)
    assert v.shape == (B, 8, 8, 3)
    fluid.reset_default_programs()
    img = L.data("img", DT.dense_vector(8 * 8 * 3))
    x = _as4(L.LayerOutput(FL.reshape(img.var, (-1, 8, 8, 3))), (B, 8, 8, 3))
    v = _run(L.bilinear_interp_layer(x, 16, 16), feeds)
    assert v.shape == (B, 16, 16, 3)
    fluid.reset_default_programs()
    img = L.data("img", DT.dense_vector(8 * 8 * 3))
    x = _as4(L.LayerOutput(FL.reshape(img.var, (-1, 8, 8, 3))), (B, 8, 8, 3))
    v = _run(L.rotate_layer(x), feeds)
    assert v.shape == (B, 8, 8, 3)
    fluid.reset_default_programs()
    img = L.data("img", DT.dense_vector(8 * 8 * 3))
    x = _as4(L.LayerOutput(FL.reshape(img.var, (-1, 8, 8, 3))), (B, 8, 8, 3))
    v = _run(L.spp_layer(x, pyramid_height=2), feeds)
    assert v.shape == (B, 5 * 3)
    fluid.reset_default_programs()
    img = L.data("img", DT.dense_vector(8 * 8 * 3))
    x = _as4(L.LayerOutput(FL.reshape(img.var, (-1, 8, 8, 3))), (B, 8, 8, 3))
    v = _run(L.img_conv_transpose(x, 4, 3, stride=2), feeds)
    assert v.shape[0] == B and v.shape[-1] == 4
    fluid.reset_default_programs()
    img = L.data("img", DT.dense_vector(4 * 8 * 8 * 3))
    x = _as4(L.LayerOutput(FL.reshape(img.var, (-1, 4, 8, 8, 3))),
             (B, 4, 8, 8, 3))
    feeds5 = {"img": RS.randn(B, 4 * 8 * 8 * 3).astype(np.float32)}
    v = _run(L.img_pool3d(L.img_conv3d(x, 4, 3, padding=1), 2), feeds5)
    assert v.shape[0] == B and v.shape[-1] == 4


def test_seq_aux_layers():
    s = _seq("s")
    rc = L.row_conv_layer(s, future_context=2)
    v = _run(rc, {"s": SEQ, "s__len__": LENS})
    assert v.shape == (B, T, D)
    fluid.reset_default_programs()
    s = _seq("s")
    pl = L.prelu_layer(s)
    v = _run(pl, {"s": SEQ, "s__len__": LENS})
    assert v.shape == (B, T, D)


# ------------------------------------------------------------ cost zoo -------

def _scalar(cost, feeds):
    v = _run(cost, feeds)
    assert v.shape == () and np.isfinite(v)
    return float(v)


def test_cost_zoo_regression_family():
    x, y = _dense("x"), _dense("y")
    _scalar(L.mse_cost(x, y), {"x": X, "y": X2})
    fluid.reset_default_programs()
    x, y = _dense("x"), _dense("y")
    _scalar(L.huber_regression_cost(x, y), {"x": X, "y": X2})
    fluid.reset_default_programs()
    x, y = _dense("x"), _dense("y")
    _scalar(L.smooth_l1_cost(x, y), {"x": X, "y": X2})


def test_cost_zoo_classification_family():
    logits = _dense("l", V)
    lab = L.data("y", DT.integer_value(V))
    feeds = {"l": RS.randn(B, V).astype(np.float32),
             "y": RS.randint(0, V, B).astype(np.int32)}
    _scalar(L.cross_entropy_with_selfnorm_cost(logits, lab), feeds)
    fluid.reset_default_programs()
    logits = _dense("l", V)
    multi = L.data("m", DT.dense_vector(V))
    _scalar(L.multi_binary_label_cross_entropy_cost(logits, multi),
            {"l": RS.randn(B, V).astype(np.float32),
             "m": RS.randint(0, 2, (B, V)).astype(np.float32)})
    fluid.reset_default_programs()
    p = _dense("p", V)
    soft = L.data("t", DT.dense_vector(V))
    probs = np.abs(RS.randn(B, V)).astype(np.float32) * 0.1 + 0.2
    _scalar(L.soft_binary_class_cross_entropy_cost(p, soft),
            {"p": np.clip(probs, 0.05, 0.95),
             "t": np.clip(probs, 0.05, 0.95)})
    fluid.reset_default_programs()
    score = L.data("s", DT.dense_vector(1))
    binlab = L.data("y", DT.dense_vector(1))
    _scalar(L.sigmoid_cross_entropy_cost(score, binlab),
            {"s": RS.randn(B, 1).astype(np.float32),
             "y": RS.randint(0, 2, (B, 1)).astype(np.float32)})
    fluid.reset_default_programs()
    score = L.data("s", DT.dense_vector(1))
    pm = L.data("y", DT.dense_vector(1))
    _scalar(L.hinge_cost(score, pm),
            {"s": RS.randn(B, 1).astype(np.float32),
             "y": (RS.randint(0, 2, (B, 1)) * 2 - 1).astype(np.float32)})


def test_cost_zoo_rank_and_lambda():
    left = L.data("a", DT.dense_vector(1))
    right = L.data("b", DT.dense_vector(1))
    lab = L.data("y", DT.dense_vector(1))
    _scalar(L.rank_cost(left, right, lab),
            {"a": RS.randn(B, 1).astype(np.float32),
             "b": RS.randn(B, 1).astype(np.float32),
             "y": RS.randint(0, 2, (B, 1)).astype(np.float32)})
    fluid.reset_default_programs()
    score = L.data("s", DT.integer_value_sequence(1))  # [B, T] float scores
    score.var.dtype = "float32"
    rel = L.data("r", DT.integer_value_sequence(1))
    rel.var.dtype = "float32"
    _scalar(L.lambda_cost(score, rel),
            {"s": RS.randn(B, T).astype(np.float32),
             "r": RS.randint(0, 3, (B, T)).astype(np.float32),
             "s__len__": LENS, "r__len__": LENS})


def test_cost_zoo_structured():
    emis = _seq("e", 5)
    tags = L.data("t", DT.integer_value_sequence(5))
    _scalar(L.crf_layer(emis, tags),
            {"e": RS.randn(B, T, 5).astype(np.float32),
             "t": RS.randint(0, 5, (B, T)).astype(np.int32),
             "e__len__": LENS, "t__len__": LENS})
    fluid.reset_default_programs()
    emis = _seq("e", 5)
    tags = L.data("t", DT.integer_value_sequence(5))
    cost = L.crf_layer(emis, tags)
    # decoding SHARES the training transitions (reference: same param name)
    dec = L.crf_decoding_layer(emis, transitions=cost.transitions)
    trans_names = [v for v in
                   fluid.default_main_program().global_block().vars
                   if "crf_trans" in v]
    assert len(trans_names) == 1, trans_names
    v = _run(dec, {"e": RS.randn(B, T, 5).astype(np.float32),
                   "t": RS.randint(0, 5, (B, T)).astype(np.int32),
                   "e__len__": LENS, "t__len__": LENS})
    assert v.shape == (B, T) and (v >= 0).all() and (v < 5).all()
    fluid.reset_default_programs()
    logits = _seq("lg", 6)
    labels = L.data("lb", DT.integer_value_sequence(6))
    _scalar(L.ctc_layer(logits, labels, size=6),
            {"lg": RS.randn(B, T, 6).astype(np.float32),
             "lb": RS.randint(1, 6, (B, 3)).astype(np.int32),
             "lg__len__": LENS,
             "lb__len__": np.array([3, 2, 2, 1], np.int32)})


def test_cost_zoo_sampled():
    h = _dense("h")
    lab = L.data("y", DT.integer_value(V))
    feeds = {"h": X, "y": RS.randint(0, V, B).astype(np.int32)}
    _scalar(L.nce_layer(h, lab, num_classes=V, num_neg_samples=3), feeds)
    fluid.reset_default_programs()
    h = _dense("h")
    lab = L.data("y", DT.integer_value(V))
    _scalar(L.hsigmoid_layer(h, lab, num_classes=V), feeds)


def test_cost_trains_end_to_end():
    """A mixed_layer + cost-zoo model actually learns via the v2 trainer."""
    from paddle_tpu.trainer import event
    x = L.data("x", DT.dense_vector(D))
    y = L.data("y", DT.dense_vector(1))
    h = L.mixed_layer(size=16, input=[L.full_matrix_projection(x, 16)],
                      act="tanh", bias_attr=True)
    pred = L.fc(h, 1)
    cost = L.huber_regression_cost(pred, y)

    w_true = RS.randn(D, 1).astype(np.float32)
    Xtr = RS.randn(256, D).astype(np.float32)
    Ytr = Xtr @ w_true

    def reader():
        for i in range(0, 256, 32):
            yield [(Xtr[j], Ytr[j]) for j in range(i, i + 32)]

    trainer = paddle.SGD(cost, paddle.optimizer.Adam(1e-2))
    costs = []
    trainer.train(reader, num_passes=10,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, event.EndIteration) else None,
                  feeding=[x, y])
    assert costs[-1] < costs[0] * 0.3


def test_prebuilt_networks():
    net = paddle.networks
    words = L.data("w", DT.integer_value_sequence(V))
    emb = L.embedding(words, D)
    feeds = {"w": RS.randint(0, V, (B, T)).astype(np.int32),
             "w__len__": LENS}
    g = net.simple_gru(emb, 8)
    v = _run(g, feeds)
    assert v.shape == (B, T, 8)
    fluid.reset_default_programs()
    words = L.data("w", DT.integer_value_sequence(V))
    emb = L.embedding(words, D)
    bi = net.bidirectional_gru(emb, 8)
    v = _run(bi, feeds)
    assert v.shape == (B, 16)
    fluid.reset_default_programs()
    words = L.data("w", DT.integer_value_sequence(V))
    emb = L.embedding(words, D)
    scp = net.sequence_conv_pool(emb, context_len=3, hidden_size=10)
    v = _run(scp, feeds)
    assert v.shape == (B, 10)
    fluid.reset_default_programs()
    words = L.data("w", DT.integer_value_sequence(V))
    emb = L.embedding(words, D)
    ap = net.simple_attention_pool(emb)
    v = _run(ap, feeds)
    assert v.shape == (B, D)


def test_v2_evaluator_dsl_metrics_in_events():
    """trainer_config_helpers/evaluators.py analog: in-graph evaluators
    attached as extra layers surface per-batch metrics in EndIteration."""
    from paddle_tpu.trainer import event

    x = L.data("x", DT.dense_vector(D))
    y = L.data("y", DT.integer_value(2))
    logits = L.fc(x, 2)
    cost = L.classification_cost(logits, y)
    err = paddle.evaluator.classification_error_evaluator(logits, y)
    ssum = paddle.evaluator.sum_evaluator(logits)
    f1 = paddle.evaluator.precision_recall_evaluator(logits, y)

    rs = np.random.RandomState(0)
    Xd = rs.randn(64, D).astype(np.float32)
    Yd = (Xd.sum(-1) > 0).astype(np.int32)

    def reader():
        for i in range(0, 64, 16):
            yield [(Xd[j], int(Yd[j])) for j in range(i, i + 16)]

    seen = []
    tr = paddle.SGD(cost, paddle.optimizer.Adam(5e-2),
                    extra_layers=[err, ssum, f1])
    tr.train(reader, num_passes=4,
             event_handler=lambda e: seen.append(e.metrics)
             if isinstance(e, event.EndIteration) else None,
             feeding=[x, y])
    assert seen and all(len(m) == 3 for m in seen)
    errs = [m[err.var.name] for m in seen]
    assert 0.0 <= errs[-1] <= 1.0 and errs[-1] <= errs[0]
    f1s = [m[f1.var.name] for m in seen]
    assert 0.0 <= f1s[-1] <= 1.0 and f1s[-1] >= f1s[0]


def test_v2_auc_evaluator_from_logits():
    """auc_evaluator accepts [B, C] logits (positive-class prob extracted)."""
    x = L.data("x", DT.dense_vector(D))
    y = L.data("y", DT.integer_value(2))
    logits = L.fc(x, 2)
    auc = paddle.evaluator.auc_evaluator(logits, y)
    v = _run(auc, {"x": X, "y": RS.randint(0, 2, B).astype(np.int32)})
    assert 0.0 <= float(v) <= 1.0
