"""Gen-1 layer-zoo breadth: every new *_layer / *_cost in the v2 DSL builds
a program and executes through the fluid Executor — the parametrized analog
of trainer_config_helpers' per-layer configs + test_LayerGrad coverage
(SURVEY.md §2.4: layers.py 106 *_layer functions; CostLayer.cpp zoo)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.v2 as paddle
from paddle_tpu.fluid.executor import Executor

L = paddle.layer
DT = paddle.data_type

B, T, D, V = 4, 6, 8, 12


@pytest.fixture(autouse=True)
def fresh_programs():
    fluid.reset_default_programs()
    yield


def _run(out_layer, feeds):
    exe = Executor()
    exe.run(fluid.default_startup_program())
    res = exe.run(fluid.default_main_program(), feed=feeds,
                  fetch_list=[out_layer.var.name])
    return np.asarray(res[0])


def _dense(name, dim=D):
    return L.data(name, DT.dense_vector(dim))


def _seq(name, dim=D):
    return L.data(name, DT.dense_vector_sequence(dim))


RS = np.random.RandomState(0)
X = RS.randn(B, D).astype(np.float32)
X2 = RS.randn(B, D).astype(np.float32)
SEQ = RS.randn(B, T, D).astype(np.float32)
LENS = np.array([6, 4, 3, 2], np.int32)


# ----------------------------------------------------------- mixed/proj ------

def test_mixed_layer_full_matrix_plus_identity():
    x = _dense("x")
    out = L.mixed_layer(size=D, input=[
        L.full_matrix_projection(x, D),
        L.identity_projection(x),
        L.dotmul_projection(x),
    ], act="tanh", bias_attr=True)
    v = _run(out, {"x": X})
    assert v.shape == (B, D) and np.isfinite(v).all()


def test_mixed_layer_table_and_trans():
    ids = L.data("ids", DT.integer_value(V))
    x = _dense("x")
    out = L.mixed_layer(size=5, input=[
        L.table_projection(ids, 5),
        L.trans_full_matrix_projection(x, 5),
        L.scaling_projection(x) if D == 5 else L.full_matrix_projection(x, 5),
    ])
    v = _run(out, {"ids": RS.randint(0, V, B).astype(np.int32), "x": X})
    assert v.shape == (B, 5)


def test_context_projection_in_mixed():
    s = _seq("s")
    out = L.mixed_layer(size=3 * D, input=[
        L.context_projection_layer(s, context_len=3)])
    v = _run(out, {"s": SEQ, "s__len__": LENS})
    assert v.shape == (B, T, 3 * D)


def test_dotmul_operator():
    a, b = _dense("a"), _dense("b")
    out = L.mixed_layer(size=D, input=[L.dotmul_operator(a, b, scale=2.0)])
    v = _run(out, {"a": X, "b": X2})
    np.testing.assert_allclose(v, 2.0 * X * X2, rtol=1e-5)


def test_identity_projection_offset_slice():
    x = _dense("x")
    out = L.mixed_layer(size=3, input=[
        L.identity_projection(x, offset=2, size=3)])
    v = _run(out, {"x": X})
    np.testing.assert_allclose(v, X[:, 2:5], rtol=1e-6)


# ------------------------------------------------------------- misc ----------

def test_addto_cos_power_scaling_slope():
    a, b = _dense("a"), _dense("b")
    added = L.addto_layer([a, b], act="relu")
    w = L.data("w", DT.dense_vector(1))
    scaled = L.scaling_layer(added, w)
    sloped = L.slope_intercept_layer(scaled, slope=2.0, intercept=1.0)
    v = _run(sloped, {"a": X, "b": X2, "w": np.ones((B, 1), np.float32)})
    np.testing.assert_allclose(v, 2.0 * np.maximum(X + X2, 0) + 1.0,
                               rtol=1e-5)
    fluid.reset_default_programs()
    a, b = _dense("a"), _dense("b")
    cs = L.cos_sim(a, b)
    v = _run(cs, {"a": X, "b": X2})
    assert v.shape == (B,) and np.all(np.abs(v) <= 1.0 + 1e-5)
    fluid.reset_default_programs()
    x = _dense("x")
    p = L.power_layer(x)
    v = _run(p, {"x": np.abs(X) + 0.1})
    assert np.isfinite(v).all()


def test_norm_interp_comb_layers():
    x = _dense("x")
    n = L.sum_to_one_norm_layer(x)
    v = _run(n, {"x": np.abs(X) + 0.1})
    np.testing.assert_allclose(v.sum(-1), 1.0, rtol=1e-5)
    fluid.reset_default_programs()
    a, b, w = _dense("a"), _dense("b"), L.data("w", DT.dense_vector(1))
    out = L.interpolation_layer([a, b], w)
    v = _run(out, {"a": X, "b": X2, "w": np.full((B, 1), 0.3, np.float32)})
    np.testing.assert_allclose(v, 0.3 * X + 0.7 * X2, rtol=1e-5)
    fluid.reset_default_programs()
    vecs = L.data("vecs", DT.dense_vector(3 * D))
    ws = L.data("ws", DT.dense_vector(3))
    out = L.linear_comb_layer(ws, vecs, D)
    v = _run(out, {"vecs": RS.randn(B, 3 * D).astype(np.float32),
                   "ws": RS.randn(B, 3).astype(np.float32)})
    assert v.shape == (B, D)


def test_shape_layers():
    x = _dense("x")
    r = L.repeat_layer(x, 3)
    v = _run(r, {"x": X})
    assert v.shape == (B, 3 * D)
    fluid.reset_default_programs()
    s = _seq("s")
    rs = L.seq_reshape_layer(s, D // 2)
    v = _run(rs, {"s": SEQ, "s__len__": LENS})
    assert v.shape == (B, 2 * T, D // 2)
    fluid.reset_default_programs()
    x = _dense("x")
    c = L.clip_layer(x, -0.5, 0.5)
    v = _run(c, {"x": X})
    assert v.min() >= -0.5 and v.max() <= 0.5
    fluid.reset_default_programs()
    x = _dense("x")
    pd = L.pad_layer(x, [(0, 0), (1, 2)])
    v = _run(pd, {"x": X})
    assert v.shape == (B, D + 3)


def test_expand_and_maxid_sampling():
    per_seq = _dense("p")
    s = _seq("s")
    ex = L.expand_layer(per_seq, s)
    v = _run(ex, {"p": X, "s": SEQ, "s__len__": LENS})
    assert v.shape == (B, T, D)
    np.testing.assert_allclose(v[:, 0], X, rtol=1e-6)
    fluid.reset_default_programs()
    x = _dense("x")
    mid = L.max_id_layer(x)
    v = _run(mid, {"x": X})
    np.testing.assert_array_equal(v, X.argmax(-1))
    fluid.reset_default_programs()
    probs = _dense("pr")
    sid = L.sampling_id_layer(probs, seed=1)
    v = _run(sid, {"pr": np.abs(X) + 0.01})
    assert v.shape == (B,) and (0 <= v).all() and (v < D).all()


def test_multiplex_tensor_convshift():
    idx = L.data("i", DT.integer_value(2))
    a, b = _dense("a"), _dense("b")
    out = L.multiplex_layer(idx, [a, b])
    ids = np.array([0, 1, 1, 0], np.int32)
    v = _run(out, {"i": ids, "a": X, "b": X2})
    want = np.where(ids[:, None] == 0, X, X2)
    np.testing.assert_allclose(v, want, rtol=1e-6)
    fluid.reset_default_programs()
    a, b = _dense("a"), _dense("b")
    t = L.tensor_layer(a, b, size=4, act="tanh")
    v = _run(t, {"a": X, "b": X2})
    assert v.shape == (B, 4)
    fluid.reset_default_programs()
    a = _dense("a")
    k = L.data("k", DT.dense_vector(3))
    cs = L.conv_shift_layer(a, k)
    v = _run(cs, {"a": X, "k": RS.randn(B, 3).astype(np.float32)})
    assert v.shape == (B, D)


def test_image_layers():
    img = L.data("img", DT.dense_vector(8 * 8 * 3))
    # v2 images feed flat; reshape through the fluid var
    from paddle_tpu.fluid import layers as FL
    reshaped = L.LayerOutput(FL.reshape(img.var, (-1, 8, 8, 3)))
    mo = L.maxout_layer(_as4(reshaped, (B, 8, 8, 3)), groups=3)
    v = _run(mo, {"img": RS.randn(B, 8 * 8 * 3).astype(np.float32)})
    assert v.shape == (B, 8, 8, 1)


def _as4(lo, shape):
    lo.var.shape = shape  # annotate for the DSL's static-shape math
    return lo


def test_image_pipeline_layers():
    from paddle_tpu.fluid import layers as FL
    img = L.data("img", DT.dense_vector(8 * 8 * 3))
    x = _as4(L.LayerOutput(FL.reshape(img.var, (-1, 8, 8, 3))), (B, 8, 8, 3))
    feeds = {"img": RS.randn(B, 8 * 8 * 3).astype(np.float32)}

    v = _run(L.img_cmrnorm_layer(x, size=3), feeds)
    assert v.shape == (B, 8, 8, 3)
    fluid.reset_default_programs()
    img = L.data("img", DT.dense_vector(8 * 8 * 3))
    x = _as4(L.LayerOutput(FL.reshape(img.var, (-1, 8, 8, 3))), (B, 8, 8, 3))
    v = _run(L.bilinear_interp_layer(x, 16, 16), feeds)
    assert v.shape == (B, 16, 16, 3)
    fluid.reset_default_programs()
    img = L.data("img", DT.dense_vector(8 * 8 * 3))
    x = _as4(L.LayerOutput(FL.reshape(img.var, (-1, 8, 8, 3))), (B, 8, 8, 3))
    v = _run(L.rotate_layer(x), feeds)
    assert v.shape == (B, 8, 8, 3)
    fluid.reset_default_programs()
    img = L.data("img", DT.dense_vector(8 * 8 * 3))
    x = _as4(L.LayerOutput(FL.reshape(img.var, (-1, 8, 8, 3))), (B, 8, 8, 3))
    v = _run(L.spp_layer(x, pyramid_height=2), feeds)
    assert v.shape == (B, 5 * 3)
    fluid.reset_default_programs()
    img = L.data("img", DT.dense_vector(8 * 8 * 3))
    x = _as4(L.LayerOutput(FL.reshape(img.var, (-1, 8, 8, 3))), (B, 8, 8, 3))
    v = _run(L.img_conv_transpose(x, 4, 3, stride=2), feeds)
    assert v.shape[0] == B and v.shape[-1] == 4
    fluid.reset_default_programs()
    img = L.data("img", DT.dense_vector(4 * 8 * 8 * 3))
    x = _as4(L.LayerOutput(FL.reshape(img.var, (-1, 4, 8, 8, 3))),
             (B, 4, 8, 8, 3))
    feeds5 = {"img": RS.randn(B, 4 * 8 * 8 * 3).astype(np.float32)}
    v = _run(L.img_pool3d(L.img_conv3d(x, 4, 3, padding=1), 2), feeds5)
    assert v.shape[0] == B and v.shape[-1] == 4


def test_seq_aux_layers():
    s = _seq("s")
    rc = L.row_conv_layer(s, future_context=2)
    v = _run(rc, {"s": SEQ, "s__len__": LENS})
    assert v.shape == (B, T, D)
    fluid.reset_default_programs()
    s = _seq("s")
    pl = L.prelu_layer(s)
    v = _run(pl, {"s": SEQ, "s__len__": LENS})
    assert v.shape == (B, T, D)


# ------------------------------------------------------------ cost zoo -------

def _scalar(cost, feeds):
    v = _run(cost, feeds)
    assert v.shape == () and np.isfinite(v)
    return float(v)


def test_cost_zoo_regression_family():
    x, y = _dense("x"), _dense("y")
    _scalar(L.mse_cost(x, y), {"x": X, "y": X2})
    fluid.reset_default_programs()
    x, y = _dense("x"), _dense("y")
    _scalar(L.huber_regression_cost(x, y), {"x": X, "y": X2})
    fluid.reset_default_programs()
    x, y = _dense("x"), _dense("y")
    _scalar(L.smooth_l1_cost(x, y), {"x": X, "y": X2})


def test_cost_zoo_classification_family():
    logits = _dense("l", V)
    lab = L.data("y", DT.integer_value(V))
    feeds = {"l": RS.randn(B, V).astype(np.float32),
             "y": RS.randint(0, V, B).astype(np.int32)}
    _scalar(L.cross_entropy_with_selfnorm_cost(logits, lab), feeds)
    fluid.reset_default_programs()
    logits = _dense("l", V)
    multi = L.data("m", DT.dense_vector(V))
    _scalar(L.multi_binary_label_cross_entropy_cost(logits, multi),
            {"l": RS.randn(B, V).astype(np.float32),
             "m": RS.randint(0, 2, (B, V)).astype(np.float32)})
    fluid.reset_default_programs()
    p = _dense("p", V)
    soft = L.data("t", DT.dense_vector(V))
    probs = np.abs(RS.randn(B, V)).astype(np.float32) * 0.1 + 0.2
    _scalar(L.soft_binary_class_cross_entropy_cost(p, soft),
            {"p": np.clip(probs, 0.05, 0.95),
             "t": np.clip(probs, 0.05, 0.95)})
    fluid.reset_default_programs()
    score = L.data("s", DT.dense_vector(1))
    binlab = L.data("y", DT.dense_vector(1))
    _scalar(L.sigmoid_cross_entropy_cost(score, binlab),
            {"s": RS.randn(B, 1).astype(np.float32),
             "y": RS.randint(0, 2, (B, 1)).astype(np.float32)})
    fluid.reset_default_programs()
    score = L.data("s", DT.dense_vector(1))
    pm = L.data("y", DT.dense_vector(1))
    _scalar(L.hinge_cost(score, pm),
            {"s": RS.randn(B, 1).astype(np.float32),
             "y": (RS.randint(0, 2, (B, 1)) * 2 - 1).astype(np.float32)})


def test_cost_zoo_rank_and_lambda():
    left = L.data("a", DT.dense_vector(1))
    right = L.data("b", DT.dense_vector(1))
    lab = L.data("y", DT.dense_vector(1))
    _scalar(L.rank_cost(left, right, lab),
            {"a": RS.randn(B, 1).astype(np.float32),
             "b": RS.randn(B, 1).astype(np.float32),
             "y": RS.randint(0, 2, (B, 1)).astype(np.float32)})
    fluid.reset_default_programs()
    score = L.data("s", DT.integer_value_sequence(1))  # [B, T] float scores
    score.var.dtype = "float32"
    rel = L.data("r", DT.integer_value_sequence(1))
    rel.var.dtype = "float32"
    _scalar(L.lambda_cost(score, rel),
            {"s": RS.randn(B, T).astype(np.float32),
             "r": RS.randint(0, 3, (B, T)).astype(np.float32),
             "s__len__": LENS, "r__len__": LENS})


def test_cost_zoo_structured():
    emis = _seq("e", 5)
    tags = L.data("t", DT.integer_value_sequence(5))
    _scalar(L.crf_layer(emis, tags),
            {"e": RS.randn(B, T, 5).astype(np.float32),
             "t": RS.randint(0, 5, (B, T)).astype(np.int32),
             "e__len__": LENS, "t__len__": LENS})
    fluid.reset_default_programs()
    emis = _seq("e", 5)
    tags = L.data("t", DT.integer_value_sequence(5))
    cost = L.crf_layer(emis, tags)
    # decoding SHARES the training transitions (reference: same param name)
    dec = L.crf_decoding_layer(emis, transitions=cost.transitions)
    trans_names = [v for v in
                   fluid.default_main_program().global_block().vars
                   if "crf_trans" in v]
    assert len(trans_names) == 1, trans_names
    v = _run(dec, {"e": RS.randn(B, T, 5).astype(np.float32),
                   "t": RS.randint(0, 5, (B, T)).astype(np.int32),
                   "e__len__": LENS, "t__len__": LENS})
    assert v.shape == (B, T) and (v >= 0).all() and (v < 5).all()
    fluid.reset_default_programs()
    logits = _seq("lg", 6)
    labels = L.data("lb", DT.integer_value_sequence(6))
    _scalar(L.ctc_layer(logits, labels, size=6),
            {"lg": RS.randn(B, T, 6).astype(np.float32),
             "lb": RS.randint(1, 6, (B, 3)).astype(np.int32),
             "lg__len__": LENS,
             "lb__len__": np.array([3, 2, 2, 1], np.int32)})


def test_cost_zoo_sampled():
    h = _dense("h")
    lab = L.data("y", DT.integer_value(V))
    feeds = {"h": X, "y": RS.randint(0, V, B).astype(np.int32)}
    _scalar(L.nce_layer(h, lab, num_classes=V, num_neg_samples=3), feeds)
    fluid.reset_default_programs()
    h = _dense("h")
    lab = L.data("y", DT.integer_value(V))
    _scalar(L.hsigmoid_layer(h, lab, num_classes=V), feeds)


def test_cost_trains_end_to_end():
    """A mixed_layer + cost-zoo model actually learns via the v2 trainer."""
    from paddle_tpu.trainer import event
    x = L.data("x", DT.dense_vector(D))
    y = L.data("y", DT.dense_vector(1))
    h = L.mixed_layer(size=16, input=[L.full_matrix_projection(x, 16)],
                      act="tanh", bias_attr=True)
    pred = L.fc(h, 1)
    cost = L.huber_regression_cost(pred, y)

    w_true = RS.randn(D, 1).astype(np.float32)
    Xtr = RS.randn(256, D).astype(np.float32)
    Ytr = Xtr @ w_true

    def reader():
        for i in range(0, 256, 32):
            yield [(Xtr[j], Ytr[j]) for j in range(i, i + 32)]

    trainer = paddle.SGD(cost, paddle.optimizer.Adam(1e-2))
    costs = []
    trainer.train(reader, num_passes=10,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, event.EndIteration) else None,
                  feeding=[x, y])
    assert costs[-1] < costs[0] * 0.3


def test_prebuilt_networks():
    net = paddle.networks
    words = L.data("w", DT.integer_value_sequence(V))
    emb = L.embedding(words, D)
    feeds = {"w": RS.randint(0, V, (B, T)).astype(np.int32),
             "w__len__": LENS}
    g = net.simple_gru(emb, 8)
    v = _run(g, feeds)
    assert v.shape == (B, T, 8)
    fluid.reset_default_programs()
    words = L.data("w", DT.integer_value_sequence(V))
    emb = L.embedding(words, D)
    bi = net.bidirectional_gru(emb, 8)
    v = _run(bi, feeds)
    assert v.shape == (B, 16)
    fluid.reset_default_programs()
    words = L.data("w", DT.integer_value_sequence(V))
    emb = L.embedding(words, D)
    scp = net.sequence_conv_pool(emb, context_len=3, hidden_size=10)
    v = _run(scp, feeds)
    assert v.shape == (B, 10)
    fluid.reset_default_programs()
    words = L.data("w", DT.integer_value_sequence(V))
    emb = L.embedding(words, D)
    ap = net.simple_attention_pool(emb)
    v = _run(ap, feeds)
    assert v.shape == (B, D)


def test_v2_evaluator_dsl_metrics_in_events():
    """trainer_config_helpers/evaluators.py analog: in-graph evaluators
    attached as extra layers surface per-batch metrics in EndIteration."""
    from paddle_tpu.trainer import event

    x = L.data("x", DT.dense_vector(D))
    y = L.data("y", DT.integer_value(2))
    logits = L.fc(x, 2)
    cost = L.classification_cost(logits, y)
    err = paddle.evaluator.classification_error_evaluator(logits, y)
    ssum = paddle.evaluator.sum_evaluator(logits)
    f1 = paddle.evaluator.precision_recall_evaluator(logits, y)

    rs = np.random.RandomState(0)
    Xd = rs.randn(64, D).astype(np.float32)
    Yd = (Xd.sum(-1) > 0).astype(np.int32)

    def reader():
        for i in range(0, 64, 16):
            yield [(Xd[j], int(Yd[j])) for j in range(i, i + 16)]

    seen = []
    tr = paddle.SGD(cost, paddle.optimizer.Adam(5e-2),
                    extra_layers=[err, ssum, f1])
    tr.train(reader, num_passes=4,
             event_handler=lambda e: seen.append(e.metrics)
             if isinstance(e, event.EndIteration) else None,
             feeding=[x, y])
    assert seen and all(len(m) == 3 for m in seen)
    errs = [m[err.var.name] for m in seen]
    assert 0.0 <= errs[-1] <= 1.0 and errs[-1] <= errs[0]
    f1s = [m[f1.var.name] for m in seen]
    assert 0.0 <= f1s[-1] <= 1.0 and f1s[-1] >= f1s[0]


def test_v2_auc_evaluator_from_logits():
    """auc_evaluator accepts [B, C] logits (positive-class prob extracted)."""
    x = L.data("x", DT.dense_vector(D))
    y = L.data("y", DT.integer_value(2))
    logits = L.fc(x, 2)
    auc = paddle.evaluator.auc_evaluator(logits, y)
    v = _run(auc, {"x": X, "y": RS.randint(0, 2, B).astype(np.int32)})
    assert 0.0 <= float(v) <= 1.0


# ------------------------------------------------- gen-1 tail (round 3) ------

def test_lstm_gru_step_layers_in_recurrent_group():
    """User-composed LSTM/GRU cells from step layers inside
    recurrent_group — the reference's signature capability
    (layers.py:3544 lstm_step_layer, :3642 gru_step_layer): the step net
    builds gates with mixed-style projections, lstm_step adds peephole +
    cell recurrence, and the cell memory is wired through
    get_output_layer(out, 'state')."""
    H = 5
    s = _seq("s")

    def lstm_step(x_t):
        h_mem = L.memory("h", H)
        c_mem = L.memory("c", H)
        gates = L.mixed_layer(size=4 * H, input=[
            L.full_matrix_projection(x_t, 4 * H),
            L.full_matrix_projection(h_mem, 4 * H)])
        out = L.lstm_step_layer(gates, c_mem, size=H, name="h")
        L.identity(L.get_output_layer(out, "state"), name="c")
        return out

    out = L.recurrent_group(lstm_step, s)
    last = L.last_seq(out)
    v = _run(last, {"s": SEQ, "s__len__": LENS})
    assert v.shape == (B, H) and np.isfinite(v).all()

    fluid.reset_default_programs()
    s = _seq("s2")

    def gru_step(x_t):
        h_mem = L.memory("h", H)
        xw = L.mixed_layer(size=3 * H,
                           input=[L.full_matrix_projection(x_t, 3 * H)])
        return L.gru_step_layer(xw, h_mem, size=H, name="h")

    out = L.recurrent_group(gru_step, s)
    v = _run(L.last_seq(out), {"s2": SEQ, "s2__len__": LENS})
    assert v.shape == (B, H) and np.isfinite(v).all()


def test_lstm_step_matches_builtin_lstm_without_peephole():
    """With zero peephole weights and matched parameters, a
    recurrent_group of lstm_step_layer computes exactly what the
    whole-sequence lstm op computes (the composition is real, not a
    lookalike)."""
    import jax.numpy as jnp

    from paddle_tpu.ops.rnn import lstm, lstm_peephole_step

    rs = np.random.RandomState(3)
    Bb, Tt, Dd, Hh = 3, 5, 4, 6
    x = jnp.asarray(rs.randn(Bb, Tt, Dd), np.float32)
    w = jnp.asarray(rs.randn(Dd, 4 * Hh) * 0.3, np.float32)
    u = jnp.asarray(rs.randn(Hh, 4 * Hh) * 0.3, np.float32)
    bias = jnp.asarray(rs.randn(4 * Hh) * 0.1, np.float32)
    ref_out, ref_state = lstm(x, None, w, u, bias, fused=False)

    h = jnp.zeros((Bb, Hh))
    c = jnp.zeros((Bb, Hh))
    zero_peep = jnp.zeros((3, Hh))
    for t in range(Tt):
        gates = x[:, t] @ w + h @ u
        h, c = lstm_peephole_step(gates, c, zero_peep, bias)
    np.testing.assert_allclose(np.asarray(h), np.asarray(ref_state.h),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(c), np.asarray(ref_state.c),
                               rtol=1e-5, atol=1e-5)


def test_selective_fc_and_gated_unit():
    x = _dense("x")
    sel = L.data("sel", DT.dense_vector(5))
    out = L.selective_fc_layer(x, 5, select=sel)
    mask = (RS.rand(B, 5) > 0.5).astype(np.float32)
    v = _run(out, {"x": X, "sel": mask})
    assert v.shape == (B, 5)
    assert np.all(v[mask == 0] == 0)           # unselected columns are zero

    fluid.reset_default_programs()
    s = _seq("s")
    out = L.gated_unit_layer(s, 7, act="tanh")
    assert out.lengths is not None             # sequence-ness preserved
    v = _run(out, {"s": SEQ, "s__len__": LENS})
    assert v.shape == (B, T, 7)
    assert np.all(np.abs(v) <= 1.0 + 1e-6)     # tanh * sigmoid bound


def test_elementwise_tail_layers():
    x = _dense("x")
    y = _dense("y")
    d = L.dot_prod_layer(x, y)
    v = _run(d, {"x": X, "y": X2})
    np.testing.assert_allclose(v[:, 0], (X * X2).sum(-1), rtol=1e-5)

    fluid.reset_default_programs()
    x = _dense("x")
    y = L.data("y", DT.dense_vector(3))
    o = L.out_prod_layer(x, y)
    v = _run(o, {"x": X, "y": X2[:, :3]})
    np.testing.assert_allclose(
        v.reshape(B, D, 3), np.einsum("bi,bj->bij", X, X2[:, :3]), rtol=1e-5)

    fluid.reset_default_programs()
    ids = L.data("ids", DT.integer_value(V))
    e = L.eos_layer(ids, eos_id=3)
    idv = np.array([3, 1, 3, 0], np.int32)
    v = _run(e, {"ids": idv})
    np.testing.assert_array_equal(v, (idv == 3).astype(np.int32))

    fluid.reset_default_programs()
    x = _dense("x")
    n = L.row_l2_norm_layer(x)
    v = _run(n, {"x": X})
    np.testing.assert_allclose(np.linalg.norm(v, axis=1),
                               np.ones(B), rtol=1e-4)

    fluid.reset_default_programs()
    x = _dense("x")
    ss = L.scale_shift_layer(x)
    v = _run(ss, {"x": X})        # w=1, b=0 at init
    np.testing.assert_allclose(v, X, rtol=1e-6)

    fluid.reset_default_programs()
    x = _dense("x")
    r = L.resize_layer(x, D // 2)
    v = _run(r, {"x": X})
    assert v.shape == (B * 2, D // 2)
    np.testing.assert_allclose(v.reshape(B, D), X, rtol=1e-6)


def test_cross_channel_norm_and_switch_order():
    img = L.data("img", DT.dense_vector(4 * 4 * 6))
    nchw = L.identity(img)
    nchw.var = fluid.layers.reshape(img.var, (-1, 6, 4, 4))
    sw = L.switch_order_layer(nchw)            # NCHW -> NHWC
    ccn = L.cross_channel_norm_layer(sw)
    raw = RS.randn(B, 6 * 4 * 4).astype(np.float32)
    v = _run(ccn, {"img": raw})
    assert v.shape == (B, 4, 4, 6)
    np.testing.assert_allclose(np.linalg.norm(v, axis=-1),
                               np.ones((B, 4, 4)), rtol=1e-4)


def test_sub_seq_family():
    s = _seq("s")
    offs = L.data("offs", DT.integer_value(T))
    szs = L.data("szs", DT.integer_value(T))
    sub = L.sub_seq_layer(s, offs, szs)
    off_v = np.array([1, 0, 1, 0], np.int32)
    sz_v = np.array([3, 2, 2, 2], np.int32)
    v = _run(sub, {"s": SEQ, "s__len__": LENS, "offs": off_v, "szs": sz_v})
    for bi in range(B):
        np.testing.assert_allclose(
            v[bi, :sz_v[bi]], SEQ[bi, off_v[bi]:off_v[bi] + sz_v[bi]],
            rtol=1e-6)

    fluid.reset_default_programs()
    s = _seq("s")
    ends = L.data("ends", DT.integer_value(T))
    sl = L.seq_slice_layer(s, None, ends)      # slice from the beginning
    v = _run(sl, {"s": SEQ, "s__len__": LENS,
                  "ends": np.array([2, 2, 1, 1], np.int32)})
    np.testing.assert_allclose(v[:, 0], SEQ[:, 0], rtol=1e-6)

    fluid.reset_default_programs()
    a = _seq("a")
    bseq = _seq("b")
    cat = L.seq_concat_layer(a, bseq)
    assert cat.lengths is not None
    exe = Executor()
    exe.run(fluid.default_startup_program())
    out, lens = exe.run(
        fluid.default_main_program(),
        feed={"a": SEQ, "a__len__": LENS, "b": SEQ, "b__len__": LENS},
        fetch_list=[cat.var.name, cat.lengths.name])
    lens = np.asarray(lens)
    np.testing.assert_array_equal(lens, LENS * 2)
    for bi in range(B):
        got = np.asarray(out)[bi]
        np.testing.assert_allclose(got[:LENS[bi]], SEQ[bi, :LENS[bi]],
                                   rtol=1e-6)
        np.testing.assert_allclose(got[LENS[bi]:2 * LENS[bi]],
                                   SEQ[bi, :LENS[bi]], rtol=1e-6)


def test_kmax_and_sub_nested_seq():
    scores = L.data("sc", DT.dense_vector_sequence(1))
    km = L.kmax_seq_score_layer(scores, beam_size=2)
    sv = RS.randn(B, T, 1).astype(np.float32)
    sv[0, 5] = 100.0                           # but len(0)=6 -> selectable
    sv[3, 4] = 100.0                           # len(3)=2 -> NOT selectable
    v = _run(km, {"sc": sv, "sc__len__": LENS})
    assert v.shape == (B, 2)
    assert 5 in v[0]
    assert 4 not in v[3]                       # padding never selected

    fluid.reset_default_programs()
    nested = L.data("ns", DT.dense_vector_sub_sequence(D))
    idx = L.LayerOutput(fluid.layers.data("idx", shape=(1,), dtype="int32"))
    trimmed = L.sub_nested_seq_layer(nested, idx)
    ns = RS.randn(B, 3, T, D).astype(np.float32)
    sub_lens = RS.randint(1, T + 1, (B, 3)).astype(np.int32)
    n_lens = np.full((B,), 3, np.int32)
    pick = np.array([[2], [0], [1], [2]], np.int32)
    exe = Executor()
    exe.run(fluid.default_startup_program())
    out, slo = exe.run(
        fluid.default_main_program(),
        feed={"ns": ns, "ns__sublen__": sub_lens, "ns__len__": n_lens,
              "idx": pick},
        fetch_list=[trimmed.var.name, trimmed.sub_lengths.name])
    out = np.asarray(out)
    for bi in range(B):
        np.testing.assert_allclose(out[bi, 0], ns[bi, pick[bi, 0]],
                                   rtol=1e-6)
        assert np.asarray(slo)[bi, 0] == sub_lens[bi, pick[bi, 0]]


def test_detection_dsl_trio():
    """priorbox -> multibox_loss (train) / detection_output (infer) at the
    v2 DSL level (layers.py:1114,1160,1233) over the existing detection
    ops."""
    F, IMG, P_, C = 4, 32, 4 * 4 * 4, 3   # 4x4 map, 4 priors/cell
    # (min + sqrt(min*max) + aspect 2 flipped = 4)
    feat = L.data("feat", DT.dense_vector(F * F * 8))
    img = L.data("img", DT.dense_vector(IMG * IMG * 3))
    featm = L.identity(feat)
    featm.var = fluid.layers.reshape(feat.var, (-1, F, F, 8))
    imgm = L.identity(img)
    imgm.var = fluid.layers.reshape(img.var, (-1, IMG, IMG, 3))
    pb = L.priorbox_layer(featm, imgm, aspect_ratio=[2.0],
                          variance=[0.1, 0.1, 0.2, 0.2],
                          min_size=[10.0], max_size=[20.0])
    assert pb.outputs and "variances" in pb.outputs

    loc = L.data("loc", DT.dense_vector(P_ * 4))
    conf = L.data("conf", DT.dense_vector(P_ * C))
    locm = L.identity(loc)
    locm.var = fluid.layers.reshape(loc.var, (-1, P_, 4))
    confm = L.identity(conf)
    confm.var = fluid.layers.reshape(conf.var, (-1, P_, C))

    G = 2
    gtb = L.data("gtb", DT.dense_vector(G * 4))
    gtl = fluid.layers.data("gtl", shape=(G,), dtype="int32")
    gtm = L.data("gtm", DT.dense_vector(G))
    gt = L.identity(gtb)
    gt.var = fluid.layers.reshape(gtb.var, (-1, G, 4))
    gt.outputs = {"gt_label": gtl, "gt_mask": gtm.var}

    loss = L.multibox_loss_layer(locm, confm, pb, gt, num_classes=C)
    det = L.detection_output_layer(locm, confm, pb, num_classes=C,
                                   keep_top_k=5)

    exe = Executor()
    exe.run(fluid.default_startup_program())
    feed = {"feat": RS.randn(B, F * F * 8).astype(np.float32),
            "img": RS.randn(B, IMG * IMG * 3).astype(np.float32),
            "loc": (RS.randn(B, P_ * 4) * 0.1).astype(np.float32),
            "conf": RS.randn(B, P_ * C).astype(np.float32),
            "gtb": RS.rand(B, G * 4).astype(np.float32),
            "gtl": RS.randint(1, C, (B, G)).astype(np.int32),
            "gtm": np.ones((B, G), np.float32)}
    lv, bv, sv2, vv = exe.run(
        fluid.default_main_program(), feed=feed,
        fetch_list=[loss.var.name, det.var.name,
                    det.outputs["scores"].name, det.outputs["valid"].name])
    assert np.isfinite(np.asarray(lv)).all()
    assert np.asarray(bv).shape == (B, C - 1, 5, 4)   # per non-bg class
    assert np.asarray(sv2).shape == (B, C - 1, 5)


def test_priorbox_multi_size_is_cell_major():
    """priorbox_layer with multiple min_sizes must interleave priors
    CELL-major (PriorBoxLayer.cpp: per cell, all sizes contiguous), matching
    a conv head that emits priors-per-cell — not size-major concat."""
    from paddle_tpu.ops.detection import prior_box as ref_prior_box
    F, IMG = 3, 24
    feat = L.data("feat", DT.dense_vector(F * F * 2))
    img = L.data("img", DT.dense_vector(IMG * IMG * 3))
    featm = L.identity(feat)
    featm.var = fluid.layers.reshape(feat.var, (-1, F, F, 2))
    imgm = L.identity(img)
    imgm.var = fluid.layers.reshape(img.var, (-1, IMG, IMG, 3))
    pb = L.priorbox_layer(featm, imgm, aspect_ratio=[2.0],
                          variance=[0.1, 0.1, 0.2, 0.2],
                          min_size=[6.0, 12.0], max_size=[12.0, 20.0])
    exe = Executor()
    exe.run(fluid.default_startup_program())
    got, gotv = exe.run(
        fluid.default_main_program(),
        feed={"feat": RS.randn(1, F * F * 2).astype(np.float32),
              "img": RS.randn(1, IMG * IMG * 3).astype(np.float32)},
        fetch_list=[pb.var.name, pb.outputs["variances"].name])
    # expected: per cell, size-6's 4 priors then size-12's 4 priors
    parts = [np.asarray(ref_prior_box((F, F), (IMG, IMG), mn, mx,
                                      aspect_ratios=(2.0,))[0])
             for mn, mx in ((6.0, 12.0), (12.0, 20.0))]
    per_cell = [p.reshape(F * F, -1, 4) for p in parts]
    want = np.concatenate(per_cell, axis=1).reshape(-1, 4)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)
    assert np.asarray(gotv).shape == want.shape


def test_conv_projection_and_operator_in_mixed():
    """conv_projection (trainable filter) and conv_operator (dynamic,
    input-supplied filter) as mixed_layer components (ConvProjection.cpp /
    ConvOperator.cpp)."""
    from paddle_tpu.fluid import layers as FL
    img = L.data("img", DT.dense_vector(6 * 6 * 2))
    x = _as4(L.LayerOutput(FL.reshape(img.var, (-1, 6, 6, 2))), (B, 6, 6, 2))
    out = L.mixed_layer(size=4, input=[
        L.conv_projection(x, filter_size=3, num_filters=4, padding=1)])
    v = _run(out, {"img": RS.randn(B, 6 * 6 * 2).astype(np.float32)})
    assert v.shape == (B, 6, 6, 4)

    fluid.reset_default_programs()
    img = L.data("img", DT.dense_vector(6 * 6 * 2))
    x = _as4(L.LayerOutput(FL.reshape(img.var, (-1, 6, 6, 2))), (B, 6, 6, 2))
    filt = L.data("filt", DT.dense_vector(3 * 3 * 2 * 4))
    out = L.mixed_layer(size=4, input=[
        L.conv_operator(x, filt, filter_size=3, num_filters=4, padding=1)])
    img_v = RS.randn(B, 6 * 6 * 2).astype(np.float32)
    filt_v = RS.randn(B, 3 * 3 * 2 * 4).astype(np.float32)
    v = _run(out, {"img": img_v, "filt": filt_v})
    assert v.shape == (B, 6, 6, 4)
    # layout check: the flat filter is the reference's (F, C, k, k) packing
    from paddle_tpu.ops.conv import conv2d
    xi = img_v.reshape(B, 6, 6, 2)
    for bi in range(B):
        w = filt_v[bi].reshape(4, 2, 3, 3).transpose(2, 3, 1, 0)  # HWIO
        ref = np.asarray(conv2d(xi[bi:bi + 1], w, padding=1))[0]
        np.testing.assert_allclose(v[bi], ref, rtol=2e-4, atol=2e-4)


def test_scale_sub_region_layer():
    from paddle_tpu.fluid import layers as FL
    img = L.data("img", DT.dense_vector(4 * 4 * 2))
    x = _as4(L.LayerOutput(FL.reshape(img.var, (-1, 4, 4, 2))), (B, 4, 4, 2))
    idx = L.LayerOutput(fluid.layers.data("idx", shape=(6,), dtype="int32"))
    out = L.scale_sub_region_layer(x, idx, value=3.0)
    raw = RS.randn(B, 4 * 4 * 2).astype(np.float32)
    iv = np.tile(np.array([1, 1, 1, 2, 1, 2], np.int32), (B, 1))  # c0,h0-1,w0-1
    v = _run(out, {"img": raw, "idx": iv})
    r = raw.reshape(B, 4, 4, 2)
    np.testing.assert_allclose(v[:, :2, :2, 0], r[:, :2, :2, 0] * 3.0,
                               rtol=1e-6)
    np.testing.assert_allclose(v[:, 2:, :, :], r[:, 2:, :, :], rtol=1e-6)
    np.testing.assert_allclose(v[:, :2, :2, 1], r[:, :2, :2, 1], rtol=1e-6)


def test_slice_projection_print_and_beam_ce():
    x = _dense("x")
    out = L.mixed_layer(size=5, input=[
        L.slice_projection(x, [(0, 2), (5, 8)])])
    v = _run(out, {"x": X})
    np.testing.assert_allclose(v, np.concatenate([X[:, 0:2], X[:, 5:8]], 1),
                               rtol=1e-6)

    fluid.reset_default_programs()
    x = _dense("x")
    same = L.print_layer(x)               # passthrough + printer metric
    v = _run(same, {"x": X})
    np.testing.assert_allclose(v, X, rtol=1e-6)

    fluid.reset_default_programs()
    scores = L.data("sc", DT.dense_vector(4))
    gold = L.data("g", DT.integer_value(5))
    gscore = L.data("gs", DT.dense_vector(1))
    loss = L.cross_entropy_over_beam(scores, gold, gscore)
    sc = RS.randn(B, 4).astype(np.float32)
    g = np.array([0, 4, 2, 1], np.int32)          # 4 = out-of-beam
    gs = RS.randn(B, 1).astype(np.float32)
    v = _run(loss, {"sc": sc, "g": g, "gs": gs})
    assert np.isfinite(v).all()
    # reference per-sample append-gold semantics: in-beam rows softmax over
    # K slots only; the out-of-beam row over K+1 with its gold appended
    def ce(logits, idx):
        z = logits - logits.max()
        return -(z[idx] - np.log(np.exp(z).sum()))
    want = np.mean([ce(sc[0], 0), ce(np.append(sc[1], gs[1]), 4),
                    ce(sc[2], 2), ce(sc[3], 1)])
    np.testing.assert_allclose(float(v), want, rtol=1e-4)
